"""Round-4 layer-tail: losses, normalization/activation stragglers, 3-D
conv/pool, spatial transforms, and sequence utilities.

Signatures follow the reference API.spec lines for each name (reference
python/paddle/fluid/layers/nn.py); lowerings live in ops/misc_ops.py.
"""
from __future__ import annotations

import numpy as np

from ..core.layer_helper import LayerHelper
from ..lod import lod_var_name


def _out(helper, dtype, shape=None):
    return helper.create_variable_for_type_inference(dtype, shape=shape)


# --- losses ---------------------------------------------------------------

def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = _out(helper, input.dtype, shape=input.shape)
    helper.append_op("log_loss", inputs={"Predicted": [input.name], "Labels": [label.name]},
                     outputs={"Loss": [out.name]}, attrs={"epsilon": float(epsilon)})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = _out(helper, left.dtype, shape=left.shape)
    helper.append_op("rank_loss",
                     inputs={"Label": [label.name], "Left": [left.name], "Right": [right.name]},
                     outputs={"Out": [out.name]}, attrs={})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = _out(helper, left.dtype, shape=left.shape)
    act = _out(helper, left.dtype, shape=left.shape)
    helper.append_op("margin_rank_loss",
                     inputs={"Label": [label.name], "X1": [left.name], "X2": [right.name]},
                     outputs={"Out": [out.name], "Activated": [act.name]},
                     attrs={"margin": float(margin)})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    shape = None
    if input.shape is not None:
        shape = tuple(input.shape[:-1]) + (1,)
    out = _out(helper, input.dtype, shape=shape)
    helper.append_op("bpr_loss", inputs={"X": [input.name], "Label": [label.name]},
                     outputs={"Y": [out.name]}, attrs={})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    shape = x.shape if reduction == "none" else ()
    out = _out(helper, x.dtype, shape=shape)
    helper.append_op("kldiv_loss", inputs={"X": [x.name], "Target": [target.name]},
                     outputs={"Loss": [out.name]}, attrs={"reduction": reduction})
    return out


def hinge_loss(input, label, name=None):
    """Op-parity surface for hinge_loss_op (the reference exposes the op but
    no fluid.layers wrapper; kept importable for kernel users)."""
    helper = LayerHelper("hinge_loss", name=name)
    out = _out(helper, input.dtype, shape=input.shape)
    helper.append_op("hinge_loss", inputs={"Logits": [input.name], "Labels": [label.name]},
                     outputs={"Loss": [out.name]}, attrs={})
    return out


# --- activations / norms --------------------------------------------------

def selu(x, scale=None, alpha=None, name=None):
    helper = LayerHelper("selu", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    attrs = {}
    if scale is not None:
        attrs["scale"] = float(scale)
    if alpha is not None:
        attrs["alpha"] = float(alpha)
    helper.append_op("selu", inputs={"X": [x.name]}, outputs={"Out": [out.name]}, attrs=attrs)
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = _out(helper, input.dtype, shape=input.shape)
    mid = _out(helper, input.dtype, shape=input.shape)
    helper.append_op("lrn", inputs={"X": [input.name]},
                     outputs={"Out": [out.name], "MidOut": [mid.name]},
                     attrs={"n": int(n), "k": float(k), "alpha": float(alpha),
                            "beta": float(beta)})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    shape = None
    if x.shape is not None:
        shape = (x.shape[0], x.shape[1] // groups) + tuple(x.shape[2:])
    out = _out(helper, x.dtype, shape=shape)
    helper.append_op("maxout", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"groups": int(groups)})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None, act=None):
    helper = LayerHelper("affine_channel", name=name, act=act)
    if scale is None or bias is None:
        raise ValueError(
            "affine_channel needs per-channel scale and bias variables "
            "(the reference kernel has no default-parameter path either)")
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op("affine_channel",
                     inputs={"X": [x.name], "Scale": [scale.name], "Bias": [bias.name]},
                     outputs={"Out": [out.name]}, attrs={"data_layout": data_layout})
    return helper.append_activation(out)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference layers/nn.py spectral_norm: creates persistable U/V vectors
    and emits the power-iteration normalization op."""
    helper = LayerHelper("spectral_norm", name=name)
    shape = weight.shape
    perm_rows = shape[dim]
    cols = int(np.prod([d for i, d in enumerate(shape) if i != dim]))
    u = helper.create_parameter(None, [1, perm_rows], "float32")
    v = helper.create_parameter(None, [1, cols], "float32")
    u.stop_gradient = True
    v.stop_gradient = True
    out = _out(helper, weight.dtype, shape=shape)
    helper.append_op("spectral_norm",
                     inputs={"Weight": [weight.name], "U": [u.name], "V": [v.name]},
                     outputs={"Out": [out.name]},
                     attrs={"dim": int(dim), "power_iters": int(power_iters),
                            "eps": float(eps)})
    return out


# --- tensor utilities -----------------------------------------------------

def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = _out(helper, inputs[0].dtype, shape=inputs[0].shape)
    helper.append_op("multiplex", inputs={"X": [v.name for v in inputs],
                                          "Ids": [index.name]},
                     outputs={"Out": [out.name]}, attrs={})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op("reverse", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"axis": axis if isinstance(axis, (list, tuple)) else [axis]})
    return out


def diag(diagonal):
    helper = LayerHelper("diag")
    shape = None
    if diagonal.shape is not None:
        n = int(np.prod(diagonal.shape))
        shape = (n, n)
    out = _out(helper, diagonal.dtype, shape=shape)
    helper.append_op("diag", inputs={"Diagonal": [diagonal.name]},
                     outputs={"Out": [out.name]}, attrs={})
    return out


# --- 3-D conv / pool ------------------------------------------------------

def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    helper = LayerHelper("conv3d", name=name, act=act)
    groups = groups or 1

    def _triple(v):
        return [v] * 3 if isinstance(v, int) else list(v)

    fsize = _triple(filter_size)
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    cin = input.shape[1]
    w = helper.create_parameter(param_attr, [num_filters, cin // groups] + fsize,
                                input.dtype)
    shape = None
    if input.shape is not None and None not in input.shape[2:]:
        sp = [
            (input.shape[2 + i] + 2 * padding[i]
             - (dilation[i] * (fsize[i] - 1) + 1)) // stride[i] + 1
            for i in range(3)
        ]
        shape = (input.shape[0], num_filters) + tuple(sp)
    out = _out(helper, input.dtype, shape=shape)
    helper.append_op("conv3d", inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [out.name]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups})
    out = helper.append_bias_op(out, bias_attr, [num_filters], dim_start=1)
    return helper.append_activation(out)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, name=None,
           exclusive=True):
    helper = LayerHelper("pool3d", name=name)

    def _triple(v):
        return [v] * 3 if isinstance(v, int) else list(v)

    ksize = _triple(pool_size)
    stride = _triple(pool_stride)
    padding = _triple(pool_padding)
    shape = None
    if input.shape is not None and None not in input.shape[2:] and not global_pooling:
        def odim(i):
            span = input.shape[2 + i] + 2 * padding[i] - ksize[i]
            n = -(-span // stride[i]) if ceil_mode else span // stride[i]
            return n + 1
        shape = (input.shape[0], input.shape[1]) + tuple(odim(i) for i in range(3))
    elif global_pooling:
        shape = (input.shape[0], input.shape[1], 1, 1, 1) if input.shape else None
    out = _out(helper, input.dtype, shape=shape)
    helper.append_op("pool3d", inputs={"X": [input.name]}, outputs={"Out": [out.name]},
                     attrs={"pooling_type": pool_type, "ksize": ksize,
                            "strides": stride, "paddings": padding,
                            "global_pooling": global_pooling, "exclusive": exclusive,
                            "ceil_mode": ceil_mode})
    return out


# --- spatial transforms ---------------------------------------------------

def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", name=name)
    inputs = {"Theta": [theta.name]}
    attrs = {}
    if hasattr(out_shape, "name"):  # Variable
        inputs["OutputShape"] = [out_shape.name]
        shape = None
    else:
        attrs["output_shape"] = [int(d) for d in out_shape]
        shape = (theta.shape[0] if theta.shape else None,
                 attrs["output_shape"][2], attrs["output_shape"][3], 2)
    out = _out(helper, theta.dtype, shape=shape)
    helper.append_op("affine_grid", inputs=inputs, outputs={"Output": [out.name]},
                     attrs=attrs)
    return out


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    shape = None
    if x.shape is not None and grid.shape is not None:
        shape = (x.shape[0], x.shape[1], grid.shape[1], grid.shape[2])
    out = _out(helper, x.dtype, shape=shape)
    helper.append_op("grid_sampler", inputs={"X": [x.name], "Grid": [grid.name]},
                     outputs={"Output": [out.name]}, attrs={})
    return out


# --- sequence utilities ---------------------------------------------------

def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference layers/nn.py row_conv: filter shape
    [future_context_size + 1, D] (current step + lookahead)."""
    helper = LayerHelper("row_conv", act=act)
    d = input.shape[-1]
    w = helper.create_parameter(param_attr, [future_context_size + 1, d], input.dtype)
    out = _out(helper, input.dtype, shape=input.shape)
    helper.append_op("row_conv", inputs={"X": [input.name], "Filter": [w.name]},
                     outputs={"Out": [out.name]}, attrs={})
    from .nn import _keep_lod

    _keep_lod(input, out)
    return helper.append_activation(out)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    if input_image_size is not None:
        raise NotImplementedError(
            "im2sequence: per-image dynamic sizes (input_image_size/out_stride) "
            "are a dynamic-shape feature; the TPU build supports the static "
            "batch path only")
    helper = LayerHelper("im2sequence", name=name)

    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    k = _pair(filter_size)
    s = _pair(stride)
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    shape = None
    if input.shape is not None and None not in input.shape[1:]:
        N, C, H, W = input.shape
        oh = (H + p[0] + p[2] - k[0]) // s[0] + 1
        ow = (W + p[1] + p[3] - k[1]) // s[1] + 1
        shape = (None, C * k[0] * k[1]) if N is None else (N * oh * ow, C * k[0] * k[1])
    out = _out(helper, input.dtype, shape=shape)
    helper.append_op("im2sequence", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"kernels": k, "strides": s, "paddings": list(p)})
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None):
    """reference layers/nn.py edit_distance over edit_distance_op: ragged
    int sequences (lod_level=1); returns (distances [B,1], seq_num)."""
    helper = LayerHelper("edit_distance")
    in_lod = getattr(input, "_lod_ref", None)
    lb_lod = getattr(label, "_lod_ref", None)
    if in_lod is None or lb_lod is None:
        raise ValueError("edit_distance expects ragged (lod_level=1) inputs")
    out = _out(helper, "float32")
    seq_num = _out(helper, "int32", shape=(1,))
    attrs = {"normalized": bool(normalized)}
    if ignored_tokens:
        attrs["ignored_tokens"] = list(ignored_tokens)
    helper.append_op("edit_distance",
                     inputs={"Hyps": [input.name], "Refs": [label.name],
                             "HypsLen": [in_lod.name], "RefsLen": [lb_lod.name]},
                     outputs={"Out": [out.name], "SequenceNum": [seq_num.name]},
                     attrs=attrs)
    return out, seq_num


# --- sampled / tree classifiers -------------------------------------------

def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False, custom_neg_classes=None):
    """reference layers/nn.py nce over nce_op; weight (C, D), bias (C,).
    is_sparse is accepted for source compat (grads here are dense — the
    SelectedRows path is exclusive to lookup_table)."""
    helper = LayerHelper("nce", name=name)
    d = input.shape[-1]
    num_neg_samples = int(num_neg_samples or 10)
    w = helper.create_parameter(param_attr, [num_total_classes, d], input.dtype)
    inputs = {"Input": [input.name], "Label": [label.name], "Weight": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_total_classes, 1],
                                    input.dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight.name]
    sampler_id = {"uniform": 0, "log_uniform": 1, "custom_dist": 2}[sampler]
    attrs = {"num_total_classes": int(num_total_classes),
             "num_neg_samples": num_neg_samples, "sampler": sampler_id,
             "seed": int(seed)}
    if custom_neg_classes:
        attrs["custom_neg_classes"] = [int(c) for c in custom_neg_classes]
    if custom_dist is not None:
        from .tensor import assign
        import numpy as _np

        probs = assign(_np.asarray(custom_dist, "float32"))
        inputs["CustomDistProbs"] = [probs.name]
    bshape = (input.shape[0], 1) if input.shape else None
    cost = _out(helper, input.dtype, shape=bshape)
    slog = _out(helper, input.dtype)
    slab = _out(helper, "int64")
    helper.append_op("nce", inputs=inputs,
                     outputs={"Cost": [cost.name], "SampleLogits": [slog.name],
                              "SampleLabels": [slab.name]},
                     attrs=attrs)
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """reference layers/nn.py hsigmoid over hierarchical_sigmoid_op (complete
    binary tree by default; custom trees via path_table/path_code vars)."""
    helper = LayerHelper("hierarchical_sigmoid", name=name)
    d = input.shape[-1]
    if is_custom and (path_table is None or path_code is None):
        raise ValueError("hsigmoid(is_custom=True) needs path_table and path_code")
    n_nodes = num_classes - 1
    w = helper.create_parameter(param_attr, [n_nodes, d], input.dtype)
    inputs = {"X": [input.name], "Label": [label.name], "W": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [n_nodes, 1], input.dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    if path_table is not None:
        inputs["PathTable"] = [path_table.name]
        inputs["PathCode"] = [path_code.name]
    bshape = (input.shape[0], 1) if input.shape else None
    out = _out(helper, input.dtype, shape=bshape)
    pre = _out(helper, input.dtype)
    helper.append_op("hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": [out.name], "PreOut": [pre.name]},
                     attrs={"num_classes": int(num_classes)})
    return out


# --- in-program beam search -----------------------------------------------

def beam_search(logits, seqs, scores, finished, step_idx, beam_size, end_id,
                name=None):
    """One in-program beam step (reference layers/nn.py beam_search over
    beam_search_op; LoD state redesigned as static [b, k] tensors — see
    ops/misc_ops.py).  Writes seqs/scores/finished IN PLACE so they carry
    through a surrounding layers.While."""
    helper = LayerHelper("beam_search", name=name)
    helper.append_op(
        "beam_search",
        inputs={"Logits": [logits.name], "Seqs": [seqs.name],
                "Scores": [scores.name], "Finished": [finished.name],
                "StepIdx": [step_idx.name]},
        outputs={"SelectedSeqs": [seqs.name], "SelectedScores": [scores.name],
                 "FinishedOut": [finished.name]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id)},
    )
    return seqs, scores, finished


def beam_search_decode(seqs, scores, end_id, length_penalty=0.0, name=None):
    """Extract the best beam per row (reference beam_search_decode_op)."""
    helper = LayerHelper("beam_search_decode", name=name)
    b, k, L = seqs.shape
    ids = _out(helper, seqs.dtype, shape=(b, L))
    best = _out(helper, "float32", shape=(b,))
    helper.append_op(
        "beam_search_decode",
        inputs={"Seqs": [seqs.name], "Scores": [scores.name]},
        outputs={"SentenceIds": [ids.name], "SentenceScores": [best.name]},
        attrs={"end_id": int(end_id), "length_penalty": float(length_penalty)},
    )
    return ids, best


def key_padding_bias(mask, name=None):
    """[b, Tk] 0/1 key mask -> additive [b, 1, 1, Tk] pre-softmax bias
    (0 where attendable, -1e9 on padding)."""
    helper = LayerHelper("key_padding_bias", name=name)
    out = _out(helper, "float32")
    helper.append_op("key_padding_bias", inputs={"X": [mask.name]},
                     outputs={"Out": [out.name]}, attrs={})
    return out


def logical_and(x, y, out=None, name=None):
    helper = LayerHelper("logical_and", name=name)
    if out is None:
        out = _out(helper, "bool", shape=x.shape)
    helper.append_op("logical_and", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]}, attrs={})
    return out


def logical_or(x, y, out=None, name=None):
    helper = LayerHelper("logical_or", name=name)
    if out is None:
        out = _out(helper, "bool", shape=x.shape)
    helper.append_op("logical_or", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]}, attrs={})
    return out


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not", name=name)
    if out is None:
        out = _out(helper, "bool", shape=x.shape)
    helper.append_op("logical_not", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={})
    return out


def expand(x, expand_times, name=None):
    """reference layers/nn.py expand over expand_op (jnp.tile)."""
    helper = LayerHelper("expand", name=name)
    shape = None
    if x.shape is not None:
        shape = tuple(
            (d * t) if (d is not None and d >= 0) else d
            for d, t in zip(x.shape, expand_times))
    out = _out(helper, x.dtype, shape=shape)
    helper.append_op("expand", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"expand_times": [int(t) for t in expand_times]})
    return out


def ctc_greedy_decoder(input, blank, name=None):
    """reference layers/nn.py ctc_greedy_decoder.  Ragged [*, C] input ->
    ragged decoded int tokens (padded carrier + lengths companion)."""
    from .sequence import _lod_of, _set_lod

    helper = LayerHelper("ctc_greedy_decoder", name=name)
    lod = _lod_of(input)
    out = helper.create_variable_for_type_inference("int32")
    out_lod = helper.create_variable_for_type_inference("int32")
    helper.append_op("ctc_greedy_decoder",
                     inputs={"Input": [input.name], "XLod": [lod.name]},
                     outputs={"Out": [out.name], "OutLod": [out_lod.name]},
                     attrs={"blank": blank})
    _set_lod(out, out_lod)
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_info=None):
    """reference layers/nn.py chunk_eval.  Ragged int tag sequences ->
    (precision, recall, f1, num_infer, num_label, num_correct); padded
    dense inputs may pass their lengths vector as seq_info instead."""
    from .sequence import _lod_of

    helper = LayerHelper("chunk_eval")
    lod = seq_info if seq_info is not None else _lod_of(input)
    outs = [helper.create_variable_for_type_inference(dt)
            for dt in ("float32", "float32", "float32", "int32", "int32", "int32")]
    helper.append_op(
        "chunk_eval",
        inputs={"Inference": [input.name], "Label": [label.name],
                "XLod": [lod.name]},
        outputs={"Precision": [outs[0].name], "Recall": [outs[1].name],
                 "F1-Score": [outs[2].name], "NumInferChunks": [outs[3].name],
                 "NumLabelChunks": [outs[4].name],
                 "NumCorrectChunks": [outs[5].name]},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": num_chunk_types,
               "excluded_chunk_types": list(excluded_chunk_types or [])},
    )
    return tuple(outs)


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """reference layers/nn.py sampled_softmax_with_cross_entropy: sample
    classes (log-uniform), correct the sampled logits, regular softmax CE
    over the sampled set.  Returns [N, 1] loss."""
    if use_customized_samples:
        raise NotImplementedError(
            "sampled_softmax_with_cross_entropy: customized_samples")
    if num_true != 1:
        raise NotImplementedError(
            "sampled_softmax_with_cross_entropy: num_true > 1 (the final "
            "hard-label CE indexes one true column per row)")
    from . import nn as _nn

    helper = LayerHelper("sample_logits")
    sampled = helper.create_variable_for_type_inference(logits.dtype)
    sampled_labels = helper.create_variable_for_type_inference("int32")
    samples = helper.create_variable_for_type_inference("int32")
    probs = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "sample_logits",
        inputs={"Logits": [logits.name], "Labels": [label.name]},
        outputs={"SampledLogits": [sampled.name],
                 "SampledLabels": [sampled_labels.name],
                 "Samples": [samples.name], "Probabilities": [probs.name]},
        attrs={"num_samples": num_samples,
               "remove_accidental_hits": remove_accidental_hits,
               "uniq": True},
    )
    return _nn.softmax_with_cross_entropy(sampled, sampled_labels)


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1, max_depth=2,
              act="tanh", param_attr=None, bias_attr=None, name=None):
    """TBCNN tree convolution (reference layers/nn.py tree_conv over
    tree_conv_op.h).  nodes_vector [B, N, F], edge_set [B, E, 2]
    (1-indexed, zero-padded); returns [B, N, output_size, num_filters]."""
    helper = LayerHelper("tree_conv", name=name, act=act)
    F = int(nodes_vector.shape[-1])
    w = helper.create_parameter(param_attr, [F, 3, output_size, num_filters],
                                nodes_vector.dtype)
    out = helper.create_variable_for_type_inference(nodes_vector.dtype)
    helper.append_op(
        "tree_conv",
        inputs={"NodesVector": [nodes_vector.name], "EdgeSet": [edge_set.name],
                "Filter": [w.name]},
        outputs={"Out": [out.name]},
        attrs={"max_depth": max_depth},
    )
    if bias_attr is not False:
        out = helper.append_bias_op(out, bias_attr, [num_filters], dim_start=3)
    return helper.append_activation(out)


def similarity_focus(input, axis, indexes, name=None):
    """reference layers/nn.py similarity_focus."""
    helper = LayerHelper("similarity_focus", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("similarity_focus", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"axis": axis, "indexes": list(indexes)})
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """reference layers/nn.py hash over hash_op.h (XXH64 % hash_size)."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("hash", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"mod_by": hash_size, "num_hash": num_hash})
    return out
