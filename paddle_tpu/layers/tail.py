"""API-tail layers (VERDICT r3 #6): reference `paddle.fluid.layers` entries
completing the audited surface.  Signatures mirror the reference API.spec;
most wrap one op, a few compose existing ops the way the reference python
layers do (dice_loss, npair_loss)."""
from __future__ import annotations

import builtins

import numpy as np

from ..core.layer_helper import LayerHelper
from ..core.program import default_main_program, default_startup_program
from ..core import unique_name
from . import nn as _nn
from . import tensor as _tensor
from .nn import _out


def _attr_act(op_type, attr_map, out_dtype=None):
    """factory: unary op with attrs, reference-signature wrapper."""
    def f(x, *args, name=None, **kw):
        helper = LayerHelper(op_type, name=name)
        attrs = {}
        for i, (aname, default) in enumerate(attr_map):
            val = args[i] if i < len(args) else kw.get(aname, default)
            if val is None:
                val = default
            attrs[aname] = val
        out = _out(helper, out_dtype or x.dtype, shape=x.shape)
        helper.append_op(op_type, inputs={"X": [x.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        return out

    f.__name__ = op_type
    return f


# activations with attrs (reference layers/ops.py generated surface)
elu = _attr_act("elu", [("alpha", 1.0)])
brelu = _attr_act("brelu", [("t_min", 0.0), ("t_max", 24.0)])
soft_relu = _attr_act("soft_relu", [("threshold", 40.0)])
thresholded_relu = _attr_act("thresholded_relu", [("threshold", 1.0)])
hard_shrink = _attr_act("hard_shrink", [("threshold", 0.5)])
softshrink = _attr_act("softshrink", [("lambda", 0.5)])
hard_sigmoid = _attr_act("hard_sigmoid", [("slope", 0.2), ("offset", 0.5)])
stanh = _attr_act("stanh", [("scale_a", 2.0 / 3.0), ("scale_b", 1.7159)])
swish = _attr_act("swish", [("beta", 1.0)])

# plain unary tail
acos = _nn._act_layer("acos")
asin = _nn._act_layer("asin")
atan = _nn._act_layer("atan")
rsqrt = _nn._act_layer("rsqrt")
sign = _nn._act_layer("sign")
tanh_shrink = _nn._act_layer("tanh_shrink")

def _binary_layer(op_type, out_dtype=None):
    def f(x, y, out=None, name=None, axis=-1, act=None):
        helper = LayerHelper(op_type, name=name, act=act)
        o = out if out is not None else _out(helper, out_dtype or x.dtype,
                                             shape=x.shape)
        helper.append_op(op_type, inputs={"X": [x.name], "Y": [y.name]},
                         outputs={"Out": [o.name]}, attrs={"axis": axis})
        return helper.append_activation(o) if act else o

    f.__name__ = op_type
    return f


logical_xor = _binary_layer("logical_xor", out_dtype="bool")
elementwise_mod = _binary_layer("elementwise_mod")
elementwise_floordiv = _binary_layer("elementwise_floordiv")


def less_equal(x, y, cond=None):
    helper = LayerHelper("less_equal")
    out = cond if cond is not None else _out(helper, "bool", shape=x.shape)
    helper.append_op("less_equal", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    return out


def greater_equal(x, y, cond=None):
    helper = LayerHelper("greater_equal")
    out = cond if cond is not None else _out(helper, "bool", shape=x.shape)
    helper.append_op("greater_equal", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    return out


def not_equal(x, y, cond=None):
    helper = LayerHelper("not_equal")
    out = cond if cond is not None else _out(helper, "bool", shape=x.shape)
    helper.append_op("not_equal", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    return out


def _reduce_layer(op_type):
    def f(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = _out(helper, "bool")
        dims = dim if dim is None or isinstance(dim, (list, tuple)) else [dim]
        helper.append_op(op_type, inputs={"X": [input.name]},
                         outputs={"Out": [out.name]},
                         attrs={"dim": list(dims) if dims else None,
                                "keep_dim": keep_dim})
        return out

    f.__name__ = op_type
    return f


reduce_all = _reduce_layer("reduce_all")
reduce_any = _reduce_layer("reduce_any")


def _scalar_probe(op_type):
    def f(x):
        helper = LayerHelper(op_type)
        out = _out(helper, "bool", shape=(1,))
        helper.append_op(op_type, inputs={"X": [x.name]},
                         outputs={"Out": [out.name]})
        return out

    f.__name__ = op_type
    return f


has_inf = _scalar_probe("has_inf")
has_nan = _scalar_probe("has_nan")
isfinite = _scalar_probe("isfinite")


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    out = cond if cond is not None else _out(helper, "bool", shape=(1,))
    helper.append_op("is_empty", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


# --- losses ---------------------------------------------------------------

def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    out = _out(helper, X.dtype)
    xn = _out(helper, X.dtype)
    yn = _out(helper, X.dtype)
    helper.append_op("cos_sim", inputs={"X": [X.name], "Y": [Y.name]},
                     outputs={"Out": [out.name], "XNorm": [xn.name],
                              "YNorm": [yn.name]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    out = _out(helper, x.dtype)
    diff = _out(helper, x.dtype)
    inputs = {"X": [x.name], "Y": [y.name]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight.name]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight.name]
    helper.append_op("smooth_l1_loss", inputs=inputs,
                     outputs={"Out": [out.name], "Diff": [diff.name]},
                     attrs={"sigma": 1.0 if sigma is None else sigma})
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = _out(helper, input.dtype)
    helper.append_op("teacher_student_sigmoid_loss",
                     inputs={"X": [input.name], "Label": [label.name]},
                     outputs={"Y": [out.name]},
                     attrs={"soft_max_up_bound": soft_max_up_bound,
                            "soft_max_lower_bound": soft_max_lower_bound})
    return out


def dice_loss(input, label, epsilon=1e-5):
    """reference layers/nn.py dice_loss: composed from elementwise ops —
    mean over rows of 1 - 2*|input ∩ label| / (|input| + |label| + eps)."""
    label = _tensor.cast(label, input.dtype)
    reduce_dim = list(builtins.range(1, len(input.shape)))
    inse = _nn.reduce_sum(input * label, dim=reduce_dim)
    denom = (_nn.reduce_sum(input, dim=reduce_dim)
             + _nn.reduce_sum(label, dim=reduce_dim))
    dice = 1.0 - (inse * 2.0) / (denom + epsilon)
    return _nn.reduce_mean(dice)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference layers/nn.py npair_loss: composed cross-entropy over
    anchor @ positive^T similarity + l2 on embeddings."""
    labels = _tensor.cast(_nn.reshape(labels, [-1, 1]), "float32")
    same = _tensor.cast(_eq_matrix(labels), "float32")
    norm = _nn.reduce_sum(same, dim=1, keep_dim=True)
    target = same / norm
    sim = _nn.matmul(anchor, positive, transpose_y=True)
    ce = _nn.softmax_with_cross_entropy(sim, target, soft_label=True)
    celoss = _nn.reduce_mean(ce)
    # batch-mean of per-row squared norms (robust to dynamic batch dim)
    row_l2 = _nn.reduce_sum(anchor * anchor + positive * positive, dim=1)
    l2 = _nn.scale(_nn.reduce_mean(row_l2), scale=l2_reg)
    return celoss + l2


def _eq_matrix(labels):
    from .math_sugar import binary

    lt = _nn.transpose(labels, [1, 0])
    return binary(labels, lt, "equal")


# --- shape / tensor utilities ---------------------------------------------

def rank(input):
    """reference layers/nn.py rank: the static rank as a constant tensor."""
    return _tensor.fill_constant([1], "int32", len(input.shape))


def shape(input):
    helper = LayerHelper("shape")
    out = _out(helper, "int32", shape=(len(input.shape),))
    helper.append_op("shape", inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]})
    return out


def sum(x):
    """reference layers/tensor.py sum: elementwise sum of a var list."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    helper = LayerHelper("sum")
    out = _out(helper, xs[0].dtype, shape=xs[0].shape)
    helper.append_op("sum", inputs={"X": [v.name for v in xs]},
                     outputs={"Out": [out.name]})
    return out


def sums(input, out=None):
    s = sum(input)
    if out is not None:
        return _tensor.assign(s, out)
    return s


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = _out(helper, x.dtype)
    helper.append_op("pad", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"paddings": list(paddings), "pad_value": pad_value})
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """reference pad_constant_like_op.cc: pad y up to x's shape.  Dims x
    doesn't know statically (the batch dim, -1) are left unpadded."""
    paddings = []
    for xd, yd in zip(x.shape, y.shape):
        delta = int(xd) - int(yd) if xd is not None and int(xd) > 0 else 0
        paddings += [0, max(delta, 0)]
    return pad(y, paddings, pad_value=pad_value, name=name)


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    n = num if num is not None else x.shape[axis]
    if n is None or int(n) < 0:
        raise ValueError(
            f"unstack: dim {axis} is dynamic ({n}); pass num= explicitly "
            "(reference raises the same)")
    n = int(n)
    outs = [_out(helper, x.dtype) for _ in builtins.range(n)]
    helper.append_op("unstack", inputs={"X": [x.name]},
                     outputs={"Y": [o.name for o in outs]},
                     attrs={"axis": axis, "num": n})
    return outs


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.main_block.create_var(
        name or unique_name.generate("create_tensor"), dtype=dtype,
        persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.param_attr import ParamAttr

    helper = LayerHelper("create_parameter")
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias=is_bias,
                                   default_initializer=default_initializer)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference layers/tensor.py: a persistable int counter incremented
    once per executed step."""
    name = counter_name or "@STEP_COUNTER@"
    main = default_main_program().global_block()
    if main.has_var(name):
        return main.var(name)
    counter = main.create_var(name, shape=(1,), dtype="int64", persistable=True)
    startup = default_startup_program().global_block()
    startup.create_var(name, shape=(1,), dtype="int64", persistable=True)
    startup.append_op("fill_constant", outputs={"Out": [name]},
                      attrs={"shape": [1], "dtype": "int64",
                             "value": float(begin - step)})
    main.append_op("increment", inputs={"X": [name]}, outputs={"Out": [name]},
                   attrs={"step": float(step)})
    return counter


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = _out(helper, dtype)
    helper.append_op("fill_constant_batch_size_like",
                     inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = _out(helper, dtype)
    helper.append_op("uniform_random_batch_size_like",
                     inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx,
                            "min": min, "max": max, "seed": seed})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = _out(helper, dtype)
    helper.append_op("gaussian_random_batch_size_like",
                     inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx,
                            "mean": mean, "std": std, "seed": seed})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = _out(helper, dtype, shape=tuple(shape))
    helper.append_op("uniform_random", outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "min": min, "max": max, "seed": seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = _out(helper, dtype, shape=tuple(shape))
    helper.append_op("gaussian_random", outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "mean": mean, "std": std, "seed": seed})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("sampling_id")
    out = _out(helper, "int32", shape=(x.shape[0],))
    helper.append_op("sampling_id", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"min": min, "max": max, "seed": seed})
    return out


def range(start, end, step, dtype):
    helper = LayerHelper("range")
    out = _out(helper, dtype)
    inputs, attrs = {}, {"dtype": dtype}
    for slot, key, v in (("Start", "start_v", start), ("End", "end_v", end),
                         ("Step", "step_v", step)):
        if hasattr(v, "name"):
            inputs[slot] = [v.name]
        else:
            attrs[key] = v
    helper.append_op("range", inputs=inputs, outputs={"Out": [out.name]},
                     attrs=attrs)
    return out


# --- structured ops -------------------------------------------------------

def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle")
    n, c, h, w = x.shape
    r = upscale_factor
    out = _out(helper, x.dtype, shape=(n, c // (r * r), h * r, w * r))
    helper.append_op("pixel_shuffle", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"upscale_factor": r})
    return out


def shuffle_channel(x, group, name=None):
    helper = LayerHelper("shuffle_channel", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op("shuffle_channel", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"group": group})
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    helper = LayerHelper("temporal_shift", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op("temporal_shift", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"seg_num": seg_num, "shift_ratio": shift_ratio})
    return out


def fsp_matrix(x, y):
    helper = LayerHelper("fsp")
    out = _out(helper, x.dtype, shape=(x.shape[0], x.shape[1], y.shape[1]))
    helper.append_op("fsp", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", name=name)

    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    out = _out(helper, x.dtype)
    helper.append_op("unfold", inputs={"X": [x.name]},
                     outputs={"Y": [out.name]},
                     attrs={"kernel_sizes": _pair(kernel_sizes),
                            "strides": _pair(strides),
                            "paddings": _pair(paddings),
                            "dilations": _pair(dilations)})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if require_index:
        raise NotImplementedError("adaptive_pool2d: require_index (mask "
                                  "output) is not implemented")
    helper = LayerHelper("adaptive_pool2d", name=name)
    ps = [pool_size, pool_size] if isinstance(pool_size, int) else list(pool_size)
    oshape = ((input.shape[0], input.shape[1], ps[0], ps[1])
              if input.shape is not None else None)
    out = _out(helper, input.dtype, shape=oshape)
    helper.append_op("adaptive_pool2d", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"pooled_size": ps, "pooling_type": pool_type})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if require_index:
        raise NotImplementedError("adaptive_pool3d: require_index is not "
                                  "implemented")
    helper = LayerHelper("adaptive_pool3d", name=name)
    ps = [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size)
    out = _out(helper, input.dtype)
    helper.append_op("adaptive_pool3d", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"pooled_size": ps, "pooling_type": pool_type})
    return out


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    helper = LayerHelper("add_position_encoding", name=name)
    out = _out(helper, input.dtype, shape=input.shape)
    helper.append_op("add_position_encoding", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"alpha": alpha, "beta": beta})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", name=name, act=act)
    dx, dy = int(x.shape[-1]), int(y.shape[-1])
    w = helper.create_parameter(param_attr, [size, dx, dy], x.dtype)
    out = _out(helper, x.dtype, shape=(x.shape[0], size))
    inputs = {"X": [x.name], "Y": [y.name], "Weight": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [1, size], x.dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    helper.append_op("bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out.name]})
    return helper.append_activation(out)


def continuous_value_model(input, cvm, use_cvm=True):
    helper = LayerHelper("cvm")
    out = _out(helper, input.dtype)
    helper.append_op("cvm", inputs={"X": [input.name], "CVM": [cvm.name]},
                     outputs={"Y": [out.name]}, attrs={"use_cvm": use_cvm})
    return out


def sequence_reshape(input, new_dim):
    from .sequence import _lod_of, _set_lod

    helper = LayerHelper("sequence_reshape")
    lod = _lod_of(input)
    out = _out(helper, input.dtype)
    out_lod = helper.create_variable_for_type_inference("int32")
    helper.append_op("sequence_reshape",
                     inputs={"X": [input.name], "XLod": [lod.name]},
                     outputs={"Out": [out.name], "OutLod": [out_lod.name]},
                     attrs={"new_dim": new_dim})
    _set_lod(out, out_lod)
    return out


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    """reference layers/nn.py data_norm: normalization by accumulated batch
    statistics with three persistable accumulators."""
    helper = LayerHelper("data_norm", name=name, act=act)
    d = int(input.shape[-1])

    def _acc(suffix, value):
        vname = unique_name.generate(f"data_norm.{suffix}")
        main = helper.main_program.global_block()
        v = main.create_var(vname, shape=(d,), dtype="float32", persistable=True)
        startup = default_startup_program().global_block()
        startup.create_var(vname, shape=(d,), dtype="float32", persistable=True)
        startup.append_op("fill_constant", outputs={"Out": [vname]},
                          attrs={"shape": [d], "dtype": "float32",
                                 "value": value})
        return v

    size = _acc("batch_size", 1e4)
    xsum = _acc("batch_sum", 0.0)
    sqs = _acc("batch_square_sum", 1e4)
    y = _out(helper, input.dtype, shape=input.shape)
    means = _out(helper, "float32")
    scales = _out(helper, "float32")
    helper.append_op(
        "data_norm",
        inputs={"X": [input.name], "BatchSize": [size.name],
                "BatchSum": [xsum.name], "BatchSquareSum": [sqs.name]},
        outputs={"Y": [y.name], "Means": [means.name], "Scales": [scales.name],
                 "BatchSizeOut": [size.name], "BatchSumOut": [xsum.name],
                 "BatchSquareSumOut": [sqs.name]},
        attrs={"epsilon": epsilon},
    )
    return helper.append_activation(y)


def get_tensor_from_selected_rows(x, name=None):
    helper = LayerHelper("get_tensor_from_selected_rows", name=name)
    out = _out(helper, x.dtype)
    helper.append_op("get_tensor_from_selected_rows",
                     inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def merge_selected_rows(x, name=None):
    helper = LayerHelper("merge_selected_rows", name=name)
    out = _out(helper, x.dtype)
    helper.append_op("merge_selected_rows", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """reference layers/nn.py conv3d_transpose (conv_transpose_op.cc)."""
    helper = LayerHelper("conv3d_transpose", name=name, act=act)
    groups = groups or 1

    def _triple(v):
        return [v] * 3 if isinstance(v, int) else list(v)

    st = _triple(stride)
    pd = _triple(padding)
    if filter_size is None:
        if output_size is None:
            raise ValueError("conv3d_transpose: give filter_size or "
                             "output_size")
        # out = (in-1)*stride - 2*pad + filter  =>  solve for filter
        osz = _triple(output_size)
        fs = [osz[i] - (int(input.shape[2 + i]) - 1) * st[i] + 2 * pd[i]
              for i in range(3)]
    else:
        fs = _triple(filter_size)
    num_channels = input.shape[1]
    w = helper.create_parameter(
        param_attr, [num_channels, num_filters // groups, fs[0], fs[1], fs[2]],
        input.dtype)
    pre_bias = _out(helper, input.dtype)
    helper.append_op(
        "conv3d_transpose",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={"strides": st, "paddings": pd,
               "dilations": _triple(dilation), "groups": groups},
    )
    pre_act = helper.append_bias_op(pre_bias, bias_attr, [num_filters], dim_start=1)
    return helper.append_activation(pre_act)


def prelu(x, mode, param_attr=None, name=None):
    """reference layers/nn.py prelu (modes all|channel|element)."""
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [int(x.shape[1])]
    elif mode == "element":
        shape = [int(d) for d in x.shape[1:]]
    else:
        raise ValueError(f"prelu: unknown mode {mode!r}")
    from ..core.initializer import ConstantInitializer

    alpha = helper.create_parameter(param_attr, shape, x.dtype,
                                    default_initializer=ConstantInitializer(0.25))
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op("prelu", inputs={"X": [x.name], "Alpha": [alpha.name]},
                     outputs={"Out": [out.name]}, attrs={"mode": mode})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = _out(helper, input.dtype)
    res = _out(helper, input.dtype)
    helper.append_op("huber_loss",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [out.name], "Residual": [res.name]},
                     attrs={"delta": delta})
    return out


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """reference layers/nn.py gru_unit over gru_unit_op.h; size = 3*D."""
    helper = LayerHelper("gru_unit")
    d = size // 3
    w = helper.create_parameter(param_attr, [d, 3 * d], input.dtype)
    inputs = {"Input": [input.name], "HiddenPrev": [hidden.name],
              "Weight": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [1, 3 * d], input.dtype,
                                    is_bias=True)
        inputs["Bias"] = [b.name]
    hid = _out(helper, input.dtype, shape=(input.shape[0], d))
    reset_h = _out(helper, input.dtype)
    gate = _out(helper, input.dtype)
    helper.append_op("gru_unit", inputs=inputs,
                     outputs={"Hidden": [hid.name],
                              "ResetHiddenPrev": [reset_h.name],
                              "Gate": [gate.name]},
                     attrs={"origin_mode": origin_mode})
    return hid, reset_h, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference layers/nn.py lstm_unit: fc([x, h]) -> lstm_unit op."""
    from . import nn as _nnmod

    helper = LayerHelper("lstm_unit", name=name)
    d = int(cell_t_prev.shape[1])
    concat_in = _nnmod.concat([x_t, hidden_t_prev], axis=1)
    fc_out = _nnmod.fc(concat_in, 4 * d, param_attr=param_attr,
                       bias_attr=bias_attr)
    c = _out(helper, x_t.dtype, shape=cell_t_prev.shape)
    h = _out(helper, x_t.dtype, shape=cell_t_prev.shape)
    helper.append_op("lstm_unit",
                     inputs={"X": [fc_out.name], "C_prev": [cell_t_prev.name]},
                     outputs={"C": [c.name], "H": [h.name]},
                     attrs={"forget_bias": forget_bias})
    return h, c


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    """reference layers/nn.py image_resize: dispatch on resample."""
    from . import nn as _nnmod

    if resample.upper() == "BILINEAR":
        return _nnmod.resize_bilinear(input, out_shape=out_shape, scale=scale,
                                      name=name, align_corners=align_corners)
    if resample.upper() == "NEAREST":
        return _nnmod.resize_nearest(input, out_shape=out_shape, scale=scale,
                                     name=name, align_corners=align_corners)
    raise ValueError(f"image_resize: unsupported resample {resample!r}")


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """reference layers/nn.py image_resize_short: scale so the short side
    equals out_short_len."""
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    out_shape = [int(round(h * out_short_len / short)),
                 int(round(w * out_short_len / short))]
    return image_resize(input, out_shape=out_shape, resample=resample)


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop")
    out = _out(helper, x.dtype)
    helper.append_op("random_crop", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"shape": list(shape)})
    return out



def batch(reader, batch_size):
    """reference layers/io.py batch: alias of the reader decorator (the
    reader-op stack is subsumed by the python reader pipeline)."""
    from .. import reader as _reader

    return _reader.batch(reader, batch_size)


def shuffle(reader, buffer_size):
    """reference layers/io.py shuffle: reader-decorator alias."""
    from .. import reader as _reader

    return _reader.shuffle(reader, buffer_size)


def double_buffer(reader, place=None, name=None):
    """reference layers/io.py double_buffer: the DataLoader's background
    prefetch thread is the TPU-native double buffer; pass-through here."""
    return reader


def load(out, file_path, load_as_fp16=None):
    """reference layers/io.py load op: read one saved variable into `out`
    at build time via the io module."""
    from .. import io as _io

    raise NotImplementedError(
        "layers.load: use fluid.io.load_vars/load_persistables (program-"
        "level load ops have no XLA residue; IO happens host-side)")


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=None,
                    deformable_groups=None, im2col_step=None,
                    param_attr=None, bias_attr=None, modulated=True,
                    name=None):
    """Deformable conv v1/v2 (reference layers/nn.py:11965).  `mask` None
    (or modulated=False) selects v1."""
    helper = LayerHelper("deformable_conv", name=name)
    groups = groups or 1
    deformable_groups = deformable_groups or 1

    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    fs = _pair(filter_size)
    if input.shape is None:
        raise ValueError("deformable_conv: input needs a static channel "
                         "count (shape is None)")
    num_channels = int(input.shape[1])
    w = helper.create_parameter(
        param_attr, [num_filters, num_channels // groups, fs[0], fs[1]],
        input.dtype)
    st, pd, dl = _pair(stride), _pair(padding), _pair(dilation)
    oh = (int(input.shape[2]) + 2 * pd[0] - (dl[0] * (fs[0] - 1) + 1)) // st[0] + 1
    ow = (int(input.shape[3]) + 2 * pd[1] - (dl[1] * (fs[1] - 1) + 1)) // st[1] + 1
    pre_bias = _out(helper, input.dtype,
                    shape=(input.shape[0], num_filters, oh, ow))
    inputs = {"Input": [input.name], "Offset": [offset.name],
              "Filter": [w.name]}
    if modulated and mask is not None:
        inputs["Mask"] = [mask.name]
    helper.append_op(
        "deformable_conv", inputs=inputs,
        outputs={"Output": [pre_bias.name]},
        attrs={"strides": _pair(stride), "paddings": _pair(padding),
               "dilations": _pair(dilation), "groups": groups,
               "deformable_groups": deformable_groups},
    )
    pre_act = helper.append_bias_op(pre_bias, bias_attr, [num_filters],
                                    dim_start=1)
    return pre_act


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           rois_batch=None, name=None):
    """reference layers/nn.py:12250 deformable_roi_pooling over
    deformable_psroi_pooling_op.h; dense [R, 4] rois + optional batch
    vector (static-shape form)."""
    helper = LayerHelper("deformable_psroi_pooling", name=name)
    c_in = int(input.shape[1])
    gh, gw = (group_size if isinstance(group_size, (list, tuple))
              else (group_size, group_size))
    # reference layers/nn.py: position-sensitive pooling divides channels
    # by the POOLED grid (each bin owns its channel slice)
    output_dim = (c_in // (pooled_height * pooled_width)
                  if position_sensitive else c_in)
    if part_size is None:
        part_size = (pooled_height, pooled_width)
    out = _out(helper, input.dtype)
    cnt = _out(helper, "float32")
    inputs = {"Input": [input.name], "ROIs": [rois.name]}
    if not no_trans and trans is not None:
        inputs["Trans"] = [trans.name]
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch.name]
    helper.append_op(
        "deformable_psroi_pooling", inputs=inputs,
        outputs={"Output": [out.name], "TopCount": [cnt.name]},
        attrs={"no_trans": no_trans, "spatial_scale": spatial_scale,
               "output_dim": output_dim, "group_size": [gh, gw],
               "pooled_height": pooled_height, "pooled_width": pooled_width,
               "part_size": list(part_size),
               "sample_per_part": sample_per_part, "trans_std": trans_std},
    )
    return out
