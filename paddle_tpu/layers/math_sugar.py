"""Python-operator sugar on Variables (reference monkey-patches in
framework.py / layers/math_op_patch.py)."""
from __future__ import annotations

import numpy as np

from ..core.layer_helper import LayerHelper
from ..core.program import Variable


def binary(x, y, op_type: str):
    helper = LayerHelper(op_type)
    if isinstance(x, Variable) and not isinstance(y, Variable):
        scalar = float(y)
        if op_type == "elementwise_add":
            return _scale(helper, x, 1.0, scalar)
        if op_type == "elementwise_sub":
            return _scale(helper, x, 1.0, -scalar)
        if op_type == "elementwise_mul":
            return _scale(helper, x, scalar, 0.0)
        if op_type == "elementwise_div":
            return _scale(helper, x, 1.0 / scalar, 0.0)
        y = _const_like(helper, x, scalar)
    elif isinstance(y, Variable) and not isinstance(x, Variable):
        x = _const_like(helper, y, float(x))
    # output shape follows the tensor operand (broadcasting), not whichever
    # side happens to be the synthesized (1,) constant
    out_shape = x.shape
    if out_shape == (1,) and y.shape not in (None, (1,)):
        out_shape = y.shape
    # compare/logical ops produce bool, whatever the operand dtype (found
    # by the static verifier: a float-declared `equal` out is a builder bug)
    from ..core.analysis import BOOL_OUT_OPS

    out_dtype = "bool" if op_type in BOOL_OUT_OPS else x.dtype
    out = helper.create_variable_for_type_inference(out_dtype, shape=out_shape)
    helper.append_op(
        op_type,
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"axis": -1},
    )
    return out


def _scale(helper, x, scale, bias):
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(
        "scale",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"scale": scale, "bias": bias},
    )
    return out


def _const_like(helper, ref, value):
    out = helper.create_variable_for_type_inference(ref.dtype, shape=(1,))
    helper.append_op(
        "fill_constant",
        outputs={"Out": [out.name]},
        attrs={"shape": [1], "dtype": ref.dtype, "value": value},
    )
    return out
