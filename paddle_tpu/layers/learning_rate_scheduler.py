"""LR schedulers (reference: python/paddle/fluid/layers/
learning_rate_scheduler.py — noam/exponential/natural_exp/inverse_time/
polynomial/piecewise/cosine decay + linear warmup).

Each returns a Variable computed each step from a persistable global-step
counter; the optimizer takes that Variable as its learning rate.  The
decay math lowers into the same XLA program as the train step, so a
schedule costs nothing (the reference ran these as separate ops each
iteration)."""
from __future__ import annotations

import math

from ..core import unique_name
from ..core.layer_helper import LayerHelper
from ..core.program import default_main_program, default_startup_program
from . import nn, tensor

_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _global_step():
    """Persistable float32 step counter, incremented once per program run."""
    main_block = default_main_program().global_block()
    if main_block.has_var(_COUNTER_NAME):
        return main_block.var(_COUNTER_NAME)
    var = main_block.create_var(_COUNTER_NAME, shape=(1,), dtype="float32", persistable=True)
    startup = default_startup_program().global_block()
    startup.create_var(_COUNTER_NAME, shape=(1,), dtype="float32", persistable=True)
    # init to -1 so the first run's schedules see step 0 (reference
    # _decay_step_counter begins at begin-1 for the same reason)
    startup.append_op(
        "fill_constant",
        outputs={"Out": [_COUNTER_NAME]},
        attrs={"shape": [1], "dtype": "float32", "value": -1.0},
    )
    main_block.append_op(
        "increment",
        inputs={"X": [_COUNTER_NAME]},
        outputs={"Out": [_COUNTER_NAME]},
        attrs={"step": 1.0},
    )
    return var


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    step = _global_step()
    a = nn.pow(step, -0.5)
    b = step * (warmup_steps ** -1.5)
    return nn.elementwise_min(a, b) * (d_model ** -0.5) * learning_rate


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    div = step / float(decay_steps)
    if staircase:
        div = nn.floor(div)
    return _exp_decay(learning_rate, div, decay_rate)


def _exp_decay(learning_rate, div, decay_rate):
    # lr * decay_rate^div  == lr * exp(div * ln(decay_rate))
    return nn.exp(div * math.log(decay_rate)) * learning_rate


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    div = step / float(decay_steps)
    if staircase:
        div = nn.floor(div)
    return nn.exp(div * (-decay_rate)) * learning_rate


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    div = step / float(decay_steps)
    if staircase:
        div = nn.floor(div)
    denom = div * decay_rate + 1.0
    return _reciprocal(denom) * learning_rate


def _reciprocal(x):
    helper = LayerHelper("reciprocal")
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op("reciprocal", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001, power=1.0, cycle=False):
    step = _global_step()
    if cycle:
        ratio = step / float(decay_steps)
        ceil_ratio = nn.ceil(ratio)
        one = tensor.fill_constant([1], "float32", 1.0)
        mult = nn.elementwise_max(ceil_ratio, one)
        decay_var = mult * float(decay_steps)
        frac = step / decay_var
    else:
        capped = nn.elementwise_min(step, tensor.fill_constant([1], "float32", float(decay_steps)))
        frac = capped / float(decay_steps)
    base = (1.0 - frac)
    poly = nn.pow(base, power)
    return poly * (learning_rate - end_learning_rate) + end_learning_rate


def piecewise_decay(boundaries, values):
    """lr = values[i] for boundaries[i-1] <= step < boundaries[i], built from
    mask arithmetic instead of the reference's conditional blocks."""
    assert len(values) == len(boundaries) + 1
    step = _global_step()
    helper = LayerHelper("piecewise_decay")
    lr = tensor.fill_constant([1], "float32", values[-1])
    prev_bound = None
    for i, b in enumerate(boundaries):
        bound = tensor.fill_constant([1], "float32", float(b))
        below = _cast_bool(_less_than(step, bound))
        if prev_bound is None:
            mask = below
        else:
            above_prev = _cast_bool(_greater_equal(step, prev_bound))
            mask = nn.elementwise_mul(below, above_prev)
        lr = lr + mask * (values[i] - values[-1])
        prev_bound = bound
    return lr


def _less_than(x, y):
    helper = LayerHelper("less_than")
    out = helper.create_variable_for_type_inference("bool", shape=x.shape)
    helper.append_op("less_than", inputs={"X": [x.name], "Y": [y.name]}, outputs={"Out": [out.name]})
    return out


def _greater_equal(x, y):
    helper = LayerHelper("greater_equal")
    out = helper.create_variable_for_type_inference("bool", shape=x.shape)
    helper.append_op("greater_equal", inputs={"X": [x.name], "Y": [y.name]}, outputs={"Out": [out.name]})
    return out


def _cast_bool(x):
    return tensor.cast(x, "float32")


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _global_step()
    epoch = nn.floor(step / float(step_each_epoch))
    inner = epoch * (math.pi / float(epochs))
    return (nn.cos(inner) + 1.0) * 0.5 * learning_rate


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Warmup then hand off to `learning_rate` (float or schedule Variable)."""
    step = _global_step()
    wsteps = tensor.fill_constant([1], "float32", float(warmup_steps))
    in_warmup = _cast_bool(_less_than(step, wsteps))
    frac = step / float(warmup_steps)
    warm = frac * (end_lr - start_lr) + start_lr
    from ..core.program import Variable

    if isinstance(learning_rate, Variable):
        after = learning_rate
    else:
        after = tensor.fill_constant([1], "float32", float(learning_rate))
    return in_warmup * warm + (1.0 - in_warmup) * after
