"""Detection layers (reference: python/paddle/fluid/layers/detection.py)."""
from __future__ import annotations

from ..core.layer_helper import LayerHelper


def _out(helper, dtype, shape=None):
    return helper.create_variable_for_type_inference(dtype, shape=shape)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes = _out(helper, "float32")
    variances = _out(helper, "float32")
    helper.append_op(
        "prior_box",
        inputs={"Input": [input.name], "Image": [image.name]},
        outputs={"Boxes": [boxes.name], "Variances": [variances.name]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "flip": flip, "clip": clip,
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": offset,
               "min_max_aspect_ratios_order": min_max_aspect_ratios_order},
    )
    return boxes, variances


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = _out(helper, x.dtype)
    helper.append_op("iou_similarity", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]},
                     attrs={"box_normalized": box_normalized})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    helper = LayerHelper("box_coder", name=name)
    out = _out(helper, target_box.dtype)
    inputs = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var.name]
    helper.append_op("box_coder", inputs=inputs,
                     outputs={"OutputBox": [out.name]},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized, "axis": axis})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = _out(helper, x.dtype)
    scores = _out(helper, x.dtype)
    helper.append_op(
        "yolo_box",
        inputs={"X": [x.name], "ImgSize": [img_size.name]},
        outputs={"Boxes": [boxes.name], "Scores": [scores.name]},
        attrs={"anchors": list(anchors), "class_num": class_num,
               "conf_thresh": conf_thresh, "downsample_ratio": downsample_ratio},
    )
    return boxes, scores


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """Static-shape NMS: [N, keep_top_k, 6] with label -1 padding (the
    reference's LoD-shaped variable output is incompatible with XLA)."""
    if nms_eta != 1.0:
        raise NotImplementedError("multiclass_nms: adaptive NMS (nms_eta != 1) "
                                  "is not implemented")
    helper = LayerHelper("multiclass_nms", name=name)
    out = _out(helper, bboxes.dtype)
    helper.append_op(
        "multiclass_nms",
        inputs={"BBoxes": [bboxes.name], "Scores": [scores.name]},
        outputs={"Out": [out.name]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "background_label": background_label, "normalized": normalized},
    )
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
              sampling_ratio=-1, rois_batch=None, name=None):
    """Static-shape RoI Align: dense [R, 4] rois + optional [R] batch index
    (replaces the reference's LoD rois)."""
    helper = LayerHelper("roi_align", name=name)
    out = _out(helper, input.dtype)
    inputs = {"X": [input.name], "ROIs": [rois.name]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch.name]
    helper.append_op(
        "roi_align", inputs=inputs, outputs={"Out": [out.name]},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio},
    )
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    helper = LayerHelper("sigmoid_focal_loss")
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op(
        "sigmoid_focal_loss",
        inputs={"X": [x.name], "Label": [label.name], "FgNum": [fg_num.name]},
        outputs={"Out": [out.name]},
        attrs={"gamma": gamma, "alpha": alpha},
    )
    return out


def anchor_generator(input, anchor_sizes, aspect_ratios, stride,
                     variance=(0.1, 0.1, 0.2, 0.2), offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors = _out(helper, "float32")
    variances = _out(helper, "float32")
    helper.append_op(
        "anchor_generator", inputs={"Input": [input.name]},
        outputs={"Anchors": [anchors.name], "Variances": [variances.name]},
        attrs={"anchor_sizes": list(anchor_sizes),
               "aspect_ratios": list(aspect_ratios), "stride": list(stride),
               "variances": list(variance), "offset": offset},
    )
    return anchors, variances


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = _out(helper, input.dtype)
    helper.append_op("box_clip",
                     inputs={"Input": [input.name], "ImInfo": [im_info.name]},
                     outputs={"Output": [out.name]})
    return out


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios=(1.0,),
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    boxes = _out(helper, "float32")
    variances = _out(helper, "float32")
    helper.append_op(
        "density_prior_box",
        inputs={"Input": [input.name], "Image": [image.name]},
        outputs={"Boxes": [boxes.name], "Variances": [variances.name]},
        attrs={"densities": list(densities), "fixed_sizes": list(fixed_sizes),
               "fixed_ratios": list(fixed_ratios), "variances": list(variance),
               "clip": clip, "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": offset},
    )
    return boxes, variances
