"""Detection layers (reference: python/paddle/fluid/layers/detection.py)."""
from __future__ import annotations

from ..core.layer_helper import LayerHelper


def _out(helper, dtype, shape=None):
    return helper.create_variable_for_type_inference(dtype, shape=shape)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes = _out(helper, "float32")
    variances = _out(helper, "float32")
    helper.append_op(
        "prior_box",
        inputs={"Input": [input.name], "Image": [image.name]},
        outputs={"Boxes": [boxes.name], "Variances": [variances.name]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "flip": flip, "clip": clip,
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": offset,
               "min_max_aspect_ratios_order": min_max_aspect_ratios_order},
    )
    return boxes, variances


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = _out(helper, x.dtype)
    helper.append_op("iou_similarity", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]},
                     attrs={"box_normalized": box_normalized})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    helper = LayerHelper("box_coder", name=name)
    out = _out(helper, target_box.dtype)
    inputs = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var.name]
    helper.append_op("box_coder", inputs=inputs,
                     outputs={"OutputBox": [out.name]},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized, "axis": axis})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = _out(helper, x.dtype)
    scores = _out(helper, x.dtype)
    helper.append_op(
        "yolo_box",
        inputs={"X": [x.name], "ImgSize": [img_size.name]},
        outputs={"Boxes": [boxes.name], "Scores": [scores.name]},
        attrs={"anchors": list(anchors), "class_num": class_num,
               "conf_thresh": conf_thresh, "downsample_ratio": downsample_ratio},
    )
    return boxes, scores


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """Static-shape NMS: [N, keep_top_k, 6] with label -1 padding (the
    reference's LoD-shaped variable output is incompatible with XLA)."""
    if nms_eta != 1.0:
        raise NotImplementedError("multiclass_nms: adaptive NMS (nms_eta != 1) "
                                  "is not implemented")
    helper = LayerHelper("multiclass_nms", name=name)
    out = _out(helper, bboxes.dtype)
    helper.append_op(
        "multiclass_nms",
        inputs={"BBoxes": [bboxes.name], "Scores": [scores.name]},
        outputs={"Out": [out.name]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "background_label": background_label, "normalized": normalized},
    )
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
              sampling_ratio=-1, rois_batch=None, name=None):
    """Static-shape RoI Align: dense [R, 4] rois + optional [R] batch index
    (replaces the reference's LoD rois)."""
    helper = LayerHelper("roi_align", name=name)
    out = _out(helper, input.dtype)
    inputs = {"X": [input.name], "ROIs": [rois.name]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch.name]
    helper.append_op(
        "roi_align", inputs=inputs, outputs={"Out": [out.name]},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio},
    )
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    helper = LayerHelper("sigmoid_focal_loss")
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op(
        "sigmoid_focal_loss",
        inputs={"X": [x.name], "Label": [label.name], "FgNum": [fg_num.name]},
        outputs={"Out": [out.name]},
        attrs={"gamma": gamma, "alpha": alpha},
    )
    return out


def anchor_generator(input, anchor_sizes, aspect_ratios, stride,
                     variance=(0.1, 0.1, 0.2, 0.2), offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors = _out(helper, "float32")
    variances = _out(helper, "float32")
    helper.append_op(
        "anchor_generator", inputs={"Input": [input.name]},
        outputs={"Anchors": [anchors.name], "Variances": [variances.name]},
        attrs={"anchor_sizes": list(anchor_sizes),
               "aspect_ratios": list(aspect_ratios), "stride": list(stride),
               "variances": list(variance), "offset": offset},
    )
    return anchors, variances


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = _out(helper, input.dtype)
    helper.append_op("box_clip",
                     inputs={"Input": [input.name], "ImInfo": [im_info.name]},
                     outputs={"Output": [out.name]})
    return out


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios=(1.0,),
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    boxes = _out(helper, "float32")
    variances = _out(helper, "float32")
    helper.append_op(
        "density_prior_box",
        inputs={"Input": [input.name], "Image": [image.name]},
        outputs={"Boxes": [boxes.name], "Variances": [variances.name]},
        attrs={"densities": list(densities), "fixed_sizes": list(fixed_sizes),
               "fixed_ratios": list(fixed_ratios), "variances": list(variance),
               "clip": clip, "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": offset},
    )
    return boxes, variances


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """YOLOv3 loss (reference layers/detection.py:763).  gt_box [N, B, 4]
    normalized center xywh, gt_label [N, B]; returns [N] loss."""
    helper = LayerHelper("yolov3_loss", name=name)
    loss = _out(helper, x.dtype)
    obj_mask = _out(helper, x.dtype)
    match_mask = _out(helper, "int32")
    inputs = {"X": [x.name], "GTBox": [gt_box.name], "GTLabel": [gt_label.name]}
    if gt_score is not None:
        inputs["GTScore"] = [gt_score.name]
    helper.append_op(
        "yolov3_loss", inputs=inputs,
        outputs={"Loss": [loss.name], "ObjectnessMask": [obj_mask.name],
                 "GTMatchMask": [match_mask.name]},
        attrs={"anchors": list(anchors), "anchor_mask": list(anchor_mask),
               "class_num": class_num, "ignore_thresh": ignore_thresh,
               "downsample_ratio": downsample_ratio,
               "use_label_smooth": use_label_smooth},
    )
    return loss


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_batch=None, name=None):
    """Quantized-bin RoI max pool (reference layers/nn.py roi_pool); dense
    [R, 4] rois + optional [R] batch-index vector (static-shape form)."""
    helper = LayerHelper("roi_pool", name=name)
    out = _out(helper, input.dtype)
    argmax = _out(helper, "int64")
    inputs = {"X": [input.name], "ROIs": [rois.name]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch.name]
    helper.append_op(
        "roi_pool", inputs=inputs,
        outputs={"Out": [out.name], "Argmax": [argmax.name]},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": spatial_scale},
    )
    return out


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    row_lengths=None, name=None):
    """Greedy bipartite matching (reference layers/detection.py:1059).
    dist_matrix [N, R, C] dense (padded rows; pass row_lengths [N] for
    ragged gt counts).  Returns (match_indices [N, C], match_dist [N, C])."""
    helper = LayerHelper("bipartite_match", name=name)
    idx = _out(helper, "int32")
    dist = _out(helper, "float32")
    inputs = {"DistMat": [dist_matrix.name]}
    if row_lengths is not None:
        inputs["RowLod"] = [row_lengths.name]
    helper.append_op(
        "bipartite_match", inputs=inputs,
        outputs={"ColToRowMatchIndices": [idx.name],
                 "ColToRowMatchDist": [dist.name]},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold},
    )
    return idx, dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """Gather per-batch targets by match index (reference
    layers/detection.py:1145).  input [N, B, K] dense padded.  Returns
    (out [N, M, K], out_weight [N, M, 1])."""
    helper = LayerHelper("target_assign", name=name)
    out = _out(helper, input.dtype)
    wt = _out(helper, "float32")
    inputs = {"X": [input.name], "MatchIndices": [matched_indices.name]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices.name]
    helper.append_op(
        "target_assign", inputs=inputs,
        outputs={"Out": [out.name], "OutWeight": [wt.name]},
        attrs={"mismatch_value": mismatch_value},
    )
    return out, wt


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True,
                      gt_lengths=None):
    """RPN target assignment (reference layers/detection.py:221).

    STATIC-SHAPE deviation from the reference: the reference gathers
    sampled anchors into dynamic [F, 4]/[F+B, 1] tensors; XLA needs fixed
    shapes, so every return spans all M anchors and sampling lives in
    weights.  Returns (predicted_scores [N, M, 1], predicted_location
    [N, M, 4], target_label [N, M], target_bbox [N, M, 4],
    bbox_inside_weight [N, M, 4], score_weight [N, M]); the RPN loss is
    sigmoid_ce(scores, label) * score_weight + |loc - target| *
    inside_weight, identical math to the reference's gathered form."""
    helper = LayerHelper("rpn_target_assign")
    label = _out(helper, "int32")
    score_w = _out(helper, "float32")
    tgt = _out(helper, anchor_box.dtype)
    inw = _out(helper, anchor_box.dtype)
    inputs = {"Anchor": [anchor_box.name], "GtBoxes": [gt_boxes.name]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd.name]
    if im_info is not None:
        inputs["ImInfo"] = [im_info.name]
    if gt_lengths is not None:
        inputs["GtLod"] = [gt_lengths.name]
    helper.append_op(
        "rpn_target_assign", inputs=inputs,
        outputs={"TargetLabel": [label.name], "ScoreWeight": [score_w.name],
                 "TargetBBox": [tgt.name], "BBoxInsideWeight": [inw.name]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_fg_fraction": rpn_fg_fraction,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "use_random": use_random},
    )
    return cls_logits, bbox_pred, label, tgt, inw, score_w


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """RPN proposals (reference layers/detection.py:2390).  Returns
    (rpn_rois [N, post_nms_top_n, 4], rpn_roi_probs [N, post_nms_top_n, 1])
    padded static blocks (prob 0 = empty slot) in place of the reference's
    LoD output."""
    if eta != 1.0:
        raise NotImplementedError("generate_proposals: adaptive NMS (eta != 1)")
    helper = LayerHelper("generate_proposals", name=name)
    rois = _out(helper, scores.dtype)
    probs = _out(helper, scores.dtype)
    helper.append_op(
        "generate_proposals",
        inputs={"Scores": [scores.name], "BboxDeltas": [bbox_deltas.name],
                "ImInfo": [im_info.name], "Anchors": [anchors.name],
                "Variances": [variances.name]},
        outputs={"RpnRois": [rois.name], "RpnRoiProbs": [probs.name]},
        attrs={"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
               "nms_thresh": nms_thresh, "min_size": min_size},
    )
    return rois, probs


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_version="integral", gt_lengths=None):
    """Batch mAP (reference layers/detection.py:966).  detect_res
    [N, D, 6] (label, score, box; label -1 pad — multiclass_nms output),
    label [N, B, 5] (class, box) padded.  Cross-batch accumulation:
    metrics.DetectionMAP."""
    helper = LayerHelper("detection_map")
    out = _out(helper, "float32")
    inputs = {"DetectRes": [detect_res.name], "Label": [label.name]}
    if gt_lengths is not None:
        inputs["GtLod"] = [gt_lengths.name]
    helper.append_op(
        "detection_map", inputs=inputs, outputs={"MAP": [out.name]},
        attrs={"class_num": class_num, "background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version},
    )
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None,
             gt_lengths=None):
    """SSD multibox loss (reference layers/detection.py:1242).  Dense gt:
    gt_box [N, B, 4] padded + gt_lengths [N]; returns [N, 1] loss."""
    if mining_type != "max_negative":
        raise ValueError("Only support mining_type == max_negative now.")
    helper = LayerHelper("ssd_loss")
    out = _out(helper, location.dtype)
    inputs = {"Location": [location.name], "Confidence": [confidence.name],
              "GtBox": [gt_box.name], "GtLabel": [gt_label.name],
              "PriorBox": [prior_box.name]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var.name]
    if gt_lengths is not None:
        inputs["GtLod"] = [gt_lengths.name]
    helper.append_op(
        "ssd_loss", inputs=inputs, outputs={"Loss": [out.name]},
        attrs={"background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "neg_pos_ratio": neg_pos_ratio, "neg_overlap": neg_overlap,
               "loc_loss_weight": loc_loss_weight,
               "conf_loss_weight": conf_loss_weight, "normalize": normalize,
               "match_type": match_type},
    )
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (reference layers/detection.py multi_box_head):
    per-feature-map prior_box + loc/conf conv branches, concatenated to
    [N, num_priors, 4] / [N, num_priors, num_classes] plus the stacked
    priors/variances."""
    from . import nn as _nn
    from . import tensor as _tensor
    from ..ops.detection_ops import expand_aspect_ratios

    n_layer = len(inputs)
    if min_sizes is None:
        assert min_ratio is not None and max_ratio is not None
        if n_layer < 3:
            raise ValueError(
                "multi_box_head: ratio-based sizing needs >= 3 feature maps "
                "(the reference divides by num_layer - 2); pass min_sizes/"
                "max_sizes explicitly for fewer")
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_layer - 2))
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes[:n_layer - 1]
        max_sizes = [base_size * 0.20] + max_sizes[:n_layer - 1]

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        ms_list = ms if isinstance(ms, (list, tuple)) else [ms]
        mx = None
        if max_sizes:
            mxi = max_sizes[i]
            mx = mxi if isinstance(mxi, (list, tuple)) else [mxi]
        ar = aspect_ratios[i]
        ar = ar if isinstance(ar, (list, tuple)) else [ar]
        st = steps[i] if steps else (step_w[i] if step_w else 0.0,
                                     step_h[i] if step_h else 0.0)
        st = st if isinstance(st, (list, tuple)) else (st, st)
        box, var = prior_box(feat, image, ms_list, mx, ar, variance, flip,
                             clip, (float(st[0]), float(st[1])), offset,
                             min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        boxes_l.append(_nn.reshape(box, [-1, 4]))
        vars_l.append(_nn.reshape(var, [-1, 4]))
        npriors = (len(ms_list) * len(expand_aspect_ratios(ar, flip))
                   + (len(mx) if mx else 0))
        loc = _nn.conv2d(feat, npriors * 4, kernel_size, padding=pad,
                         stride=stride)
        loc = _nn.transpose(loc, [0, 2, 3, 1])
        locs.append(_nn.reshape(loc, [0, -1, 4]))
        cnf = _nn.conv2d(feat, npriors * num_classes, kernel_size,
                         padding=pad, stride=stride)
        cnf = _nn.transpose(cnf, [0, 2, 3, 1])
        confs.append(_nn.reshape(cnf, [0, -1, num_classes]))

    mbox_locs = _tensor.concat(locs, axis=1)
    mbox_confs = _tensor.concat(confs, axis=1)
    boxes = _tensor.concat(boxes_l, axis=0)
    variances = _tensor.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """SSD inference head (reference layers/detection.py:440): decode loc
    deltas against priors, softmax scores, multiclass NMS.  Static-shape
    output: [N, keep_top_k, 6] padded (label -1 empty slots)."""
    from . import nn as _nn

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    probs = _nn.softmax(scores)             # [N, P, C]
    probs_t = _nn.transpose(probs, [0, 2, 1])  # [N, C, P]
    return multiclass_nms(decoded, probs_t, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, nms_eta=nms_eta,
                          background_label=background_label)


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_batch=None, name=None):
    """Position-sensitive RoI pool (reference layers/nn.py psroi_pool);
    dense [R, 4] rois + optional batch-index vector."""
    helper = LayerHelper("psroi_pool", name=name)
    out = _out(helper, input.dtype)
    inputs = {"X": [input.name], "ROIs": [rois.name]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch.name]
    helper.append_op(
        "psroi_pool", inputs=inputs, outputs={"Out": [out.name]},
        attrs={"output_channels": output_channels,
               "spatial_scale": spatial_scale,
               "pooled_height": pooled_height, "pooled_width": pooled_width},
    )
    return out


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None, im_info=None,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4, gt_lengths=None):
    """RetinaNet target assignment (reference layers/detection.py:63).

    STATIC-SHAPE form (same deviation as rpn_target_assign): returns
    (predicted_scores, predicted_location, target_label, target_bbox,
    bbox_inside_weight, fg_num, score_weight) spanning all anchors —
    target_label holds the gt class (0 background, -1 ignored), fg_num is
    the per-image foreground count + 1 (the reference's focal-loss
    normalizer)."""
    helper = LayerHelper("retinanet_target_assign")
    label = _out(helper, "int32")
    score_w = _out(helper, "float32")
    tgt = _out(helper, anchor_box.dtype)
    inw = _out(helper, anchor_box.dtype)
    fg_num = _out(helper, "int32")
    inputs = {"Anchor": [anchor_box.name], "GtBoxes": [gt_boxes.name],
              "GtLabels": [gt_labels.name]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd.name]
    if gt_lengths is not None:
        inputs["GtLod"] = [gt_lengths.name]
    helper.append_op(
        "retinanet_target_assign", inputs=inputs,
        outputs={"TargetLabel": [label.name], "ScoreWeight": [score_w.name],
                 "TargetBBox": [tgt.name], "BBoxInsideWeight": [inw.name],
                 "FgNum": [fg_num.name]},
        attrs={"positive_overlap": positive_overlap,
               "negative_overlap": negative_overlap},
    )
    return cls_logits, bbox_pred, label, tgt, inw, fg_num, score_w


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """RetinaNet inference head (reference layers/detection.py
    retinanet_detection_output / retinanet_detection_output_op.cc): per-FPN-
    level deltas decode against their anchors, sigmoid scores, class-wise
    NMS across levels.  Static-shape [N, keep_top_k, 6] output block.
    `bboxes`/`scores`: lists of [N, Ai, 4] / [N, Ai, C]; `anchors`: list of
    [Ai, 4] pixel-space anchors."""
    from . import nn as _nn
    from . import tensor as _tensor

    box_all = _tensor.concat(bboxes, axis=1) if len(bboxes) > 1 else bboxes[0]
    score_all = _tensor.concat(scores, axis=1) if len(scores) > 1 else scores[0]
    anchor_all = (_tensor.concat(anchors, axis=0) if len(anchors) > 1
                  else anchors[0])
    decoded = box_coder(anchor_all, None, box_all,
                        code_type="decode_center_size", box_normalized=False)
    decoded = box_clip(decoded, im_info)
    probs = _nn.sigmoid(score_all)              # [N, P, C]
    probs_t = _nn.transpose(probs, [0, 2, 1])   # [N, C, P]
    return multiclass_nms(decoded, probs_t, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, nms_eta=nms_eta,
                          background_label=-1, normalized=False)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info=None, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False,
                             gt_lengths=None):
    """RCNN stage-2 RoI sampling (reference layers/detection.py
    generate_proposal_labels).  STATIC-SHAPE deviation: each image emits
    exactly batch_size_per_im rows and a SampleWeight column marks drawn
    rows — returns (rois, labels_int32, bbox_targets, bbox_inside_weights,
    bbox_outside_weights, sample_weight)."""
    if is_cls_agnostic or is_cascade_rcnn:
        raise NotImplementedError(
            "generate_proposal_labels: cls-agnostic / cascade modes")
    if class_nums is None:
        raise ValueError("generate_proposal_labels: class_nums is required")
    helper = LayerHelper("generate_proposal_labels")
    rois = _out(helper, rpn_rois.dtype)
    labels = _out(helper, "int32")
    tgt = _out(helper, rpn_rois.dtype)
    inw = _out(helper, rpn_rois.dtype)
    outw = _out(helper, rpn_rois.dtype)
    sw = _out(helper, "float32")
    inputs = {"RpnRois": [rpn_rois.name], "GtClasses": [gt_classes.name],
              "GtBoxes": [gt_boxes.name]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd.name]
    if im_info is not None:
        inputs["ImInfo"] = [im_info.name]
    if gt_lengths is not None:
        inputs["GtLod"] = [gt_lengths.name]
    helper.append_op(
        "generate_proposal_labels", inputs=inputs,
        outputs={"Rois": [rois.name], "LabelsInt32": [labels.name],
                 "BboxTargets": [tgt.name], "BboxInsideWeights": [inw.name],
                 "BboxOutsideWeights": [outw.name],
                 "SampleWeight": [sw.name]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": list(bbox_reg_weights),
               "class_nums": class_nums, "use_random": use_random},
    )
    return rois, labels, tgt, inw, outw, sw


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    """FPN level routing (reference layers/detection.py
    distribute_fpn_proposals).  STATIC-SHAPE deviation: rois are not
    physically split; every level receives the full roi tensor plus a
    [R] selection mask (pool on every level, select by mask — the
    accelerator FPN formulation), and restore_ind is the identity.
    Returns (multi_rois, restore_ind, multi_masks)."""
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    L = max_level - min_level + 1
    mask = _out(helper, "float32")
    restore = _out(helper, "int32")
    helper.append_op(
        "distribute_fpn_proposals",
        inputs={"FpnRois": [fpn_rois.name]},
        outputs={"MultiLevelMask": [mask.name], "RestoreIndex": [restore.name]},
        attrs={"min_level": min_level, "max_level": max_level,
               "refer_level": refer_level, "refer_scale": refer_scale},
    )
    from . import nn as _nn

    multi_rois = [fpn_rois] * L
    # slice the [L, R] mask into per-level [R] rows
    multi_masks = []
    for i in range(L):
        row = _nn.slice(mask, axes=[0], starts=[i], ends=[i + 1])
        multi_masks.append(_nn.reshape(row, [-1]))
    return multi_rois, restore, multi_masks


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    """reference layers/detection.py collect_fpn_proposals: global top-k
    over the concatenated per-level proposals.  Returns a padded
    [post_nms_top_n, 4] block (score 0 = empty slot)."""
    helper = LayerHelper("collect_fpn_proposals", name=name)
    rois = _out(helper, multi_rois[0].dtype)
    scores = _out(helper, "float32")
    helper.append_op(
        "collect_fpn_proposals",
        inputs={"MultiLevelRois": [r.name for r in multi_rois],
                "MultiLevelScores": [s.name for s in multi_scores]},
        outputs={"FpnRois": [rois.name], "RoisScores": [scores.name]},
        attrs={"post_nms_topN": post_nms_top_n},
    )
    return rois


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=None, name=None):
    """reference layers/detection.py box_decoder_and_assign (R-FCN):
    per-class decode + best-class assignment.  prior_box_var here is the
    4-list of variances (the reference also accepts a tensor)."""
    import numpy as _np

    helper = LayerHelper("box_decoder_and_assign", name=name)
    decoded = _out(helper, target_box.dtype)
    assigned = _out(helper, target_box.dtype)
    inputs = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name],
              "BoxScore": [box_score.name]}
    attrs = {"box_clip": float(box_clip) if box_clip is not None
             else float(_np.log(1000.0 / 16.0))}
    if isinstance(prior_box_var, (list, tuple)):
        attrs["box_var"] = list(prior_box_var)
    elif prior_box_var is not None:  # tensor variances
        inputs["PriorBoxVar"] = [prior_box_var.name]
    helper.append_op("box_decoder_and_assign", inputs=inputs,
                     outputs={"DecodeBox": [decoded.name],
                              "OutputAssignBox": [assigned.name]},
                     attrs=attrs)
    return decoded, assigned


def polygon_box_transform(input, name=None):
    """reference layers/detection.py polygon_box_transform (EAST)."""
    helper = LayerHelper("polygon_box_transform", name=name)
    out = _out(helper, input.dtype, shape=input.shape)
    helper.append_op("polygon_box_transform", inputs={"Input": [input.name]},
                     outputs={"Output": [out.name]})
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_batch=None, name=None):
    """reference layers/detection.py roi_perspective_transform; dense
    [R, 8] quad rois + optional batch-index vector."""
    helper = LayerHelper("roi_perspective_transform", name=name)
    out = _out(helper, input.dtype)
    inputs = {"X": [input.name], "ROIs": [rois.name]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch.name]
    helper.append_op(
        "roi_perspective_transform", inputs=inputs,
        outputs={"Out": [out.name]},
        attrs={"transformed_height": transformed_height,
               "transformed_width": transformed_width,
               "spatial_scale": spatial_scale},
    )
    return out


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         gt_poly_lens=None, gt_lengths=None):
    """Mask-RCNN mask targets (reference layers/detection.py
    generate_mask_labels).  STATIC-SHAPE deviation: operates on the
    generate_proposal_labels outputs; gt_segms is [N, G, P, 2] padded
    polygons (one polygon per gt) + optional point/gt counts.  Returns
    (mask_rois, roi_has_mask_int32, mask_int32)."""
    helper = LayerHelper("generate_mask_labels")
    masks = _out(helper, "int32")
    has = _out(helper, "int32")
    mask_rois = _out(helper, rois.dtype)
    inputs = {"Rois": [rois.name], "LabelsInt32": [labels_int32.name],
              "GtSegms": [gt_segms.name]}
    if gt_poly_lens is not None:
        inputs["GtPolyLens"] = [gt_poly_lens.name]
    if gt_lengths is not None:
        inputs["GtLod"] = [gt_lengths.name]
    helper.append_op(
        "generate_mask_labels", inputs=inputs,
        outputs={"MaskInt32": [masks.name], "RoiHasMaskInt32": [has.name],
                 "MaskRois": [mask_rois.name]},
        attrs={"num_classes": num_classes, "resolution": resolution},
    )
    return mask_rois, has, masks
