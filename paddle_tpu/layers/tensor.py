"""Tensor layers (reference: python/paddle/fluid/layers/tensor.py)."""
from __future__ import annotations

import numpy as np

from ..core.dtypes import canonical_dtype
from ..core.layer_helper import LayerHelper
from ..core.program import Variable


def _shape_after(shape, fn):
    return None if shape is None else fn(list(shape))


def fill_constant(shape, dtype, value, name=None):
    helper = LayerHelper("fill_constant", name=name)
    out = helper.create_variable_for_type_inference(dtype, shape=tuple(shape))
    helper.append_op(
        "fill_constant",
        outputs={"Out": [out.name]},
        attrs={"shape": list(shape), "dtype": canonical_dtype(dtype), "value": float(value)},
    )
    out.stop_gradient = True
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype, shape=x.shape)
    helper.append_op(
        "cast",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"out_dtype": canonical_dtype(dtype), "in_dtype": x.dtype},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    shape = None
    if all(v.shape is not None for v in input):
        shapes = [tuple(v.shape) for v in input]
        ax = axis % len(shapes[0])  # normalize negative axes
        rest = {s[:ax] + s[ax + 1:] for s in shapes}
        cat_dims = [s[ax] for s in shapes]
        if len(rest) == 1 and all(d is not None and d >= 0 for d in cat_dims):
            shape = shapes[0][:ax] + (sum(cat_dims),) + shapes[0][ax + 1:]
    out = helper.create_variable_for_type_inference(input[0].dtype, shape=shape)
    helper.append_op(
        "concat",
        inputs={"X": [v.name for v in input]},
        outputs={"Out": [out.name]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype, shape=input[0].shape)
    helper.append_op("sum", inputs={"X": [v.name for v in input]}, outputs={"Out": [out.name]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(str(input.dtype), shape=input.shape)
        helper.append_op(
            "assign_value",
            outputs={"Out": [output.name]},
            attrs={"values": input, "dtype": canonical_dtype(input.dtype), "shape": list(input.shape)},
        )
        return output
    if output is None:
        output = helper.create_variable_for_type_inference(input.dtype, shape=input.shape)
    helper.append_op("assign", inputs={"X": [input.name]}, outputs={"Out": [output.name]})
    return output


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op("fill_zeros_like", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("arg_max", inputs={"X": [x.name]}, outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("arg_min", inputs={"X": [x.name]}, outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    from ..core import unique_name
    from ..core.program import default_main_program, default_startup_program

    name = name if name is not None else unique_name.generate("global_var")
    main_block = default_main_program().global_block()
    var = main_block.create_var(name, shape=shape, dtype=dtype, persistable=persistable)
    startup = default_startup_program().global_block()
    sv = startup.create_var(name, shape=shape, dtype=dtype, persistable=persistable)
    startup.append_op(
        "fill_constant",
        outputs={"Out": [name]},
        attrs={"shape": list(shape), "dtype": canonical_dtype(dtype), "value": float(value)},
    )
    return var


def linspace(start, stop, num, dtype="float32", name=None):
    """num evenly spaced values in [start, stop] (reference layers.linspace).
    `num` must be a python int — XLA needs a static output length."""
    from ..core.layer_helper import LayerHelper

    helper = LayerHelper("linspace", name=name)
    out = helper.create_variable_for_type_inference(dtype, shape=(int(num),))
    s = fill_constant([1], dtype, float(start))
    e = fill_constant([1], dtype, float(stop))
    helper.append_op(
        "linspace",
        inputs={"Start": [s.name], "Stop": [e.name]},
        outputs={"Out": [out.name]},
        attrs={"num_v": int(num)},
    )
    return out
