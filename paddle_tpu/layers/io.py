"""Data-entry layers (reference: python/paddle/fluid/layers/io.py data:39)."""
from __future__ import annotations

from ..core.layer_helper import LayerHelper
from ..core.program import default_main_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True, stop_gradient=True):
    """Declare an input variable.  append_batch_size=True prefixes -1, like
    the reference; the concrete batch size binds at feed time and is part of
    the executor's compile-cache key."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().current_block()
    var = block.create_var(
        name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        is_data=True,
        stop_gradient=stop_gradient,
    )
    return var
