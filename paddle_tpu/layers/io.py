"""Data-entry layers (reference: python/paddle/fluid/layers/io.py data:39)."""
from __future__ import annotations

from ..core.layer_helper import LayerHelper
from ..core.program import default_main_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True, stop_gradient=True):
    """Declare an input variable.  append_batch_size=True prefixes -1, like
    the reference; the concrete batch size binds at feed time and is part of
    the executor's compile-cache key.

    lod_level >= 1 declares a ragged input: the padded carrier gets shape
    [-1(batch), -1(time), *shape] plus an int32 lengths companion
    `<name>@LOD` (paddle_tpu/lod.py); feeding a `fluid.LoDTensor` (or a
    list of per-sequence arrays) fills both."""
    from ..lod import lod_var_name

    shape = list(shape)
    if lod_level >= 1:
        if append_batch_size:
            shape = [-1, -1] + shape  # batch, bucketed time, *feature
        # append_batch_size=False: caller already included batch+time dims
    elif append_batch_size:
        shape = [-1] + shape
    block = default_main_program().current_block()
    var = block.create_var(
        name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        is_data=True,
        stop_gradient=stop_gradient,
    )
    if lod_level >= 1:
        lod = block.create_var(
            lod_var_name(name),
            shape=[-1],
            dtype="int32",
            is_data=True,
            stop_gradient=True,
        )
        var._lod_ref = lod
    return var
