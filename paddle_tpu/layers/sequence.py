"""Sequence (ragged/LoD) layers.

Reference surface: `python/paddle/fluid/layers/nn.py` sequence_* functions
(sequence_pool, sequence_softmax, sequence_expand:4995, sequence_conv:2173,
sequence_pad/unpad, sequence_reverse, ...) and `layers/control_flow.py:1692
DynamicRNN`.  Here a ragged variable is padded dense [batch, time, *feature]
with an int32 lengths companion (`<name>@LOD`); see paddle_tpu/lod.py.

Every layer threads the lengths companion for the caller: derived ragged
outputs carry `._lod_ref` pointing at their lengths Variable.
"""
from __future__ import annotations

from ..core import unique_name
from ..core.layer_helper import LayerHelper
from ..core.program import default_main_program
from ..lod import lod_var_name


def _lod_of(x):
    ref = getattr(x, "_lod_ref", None)
    if ref is None:
        raise ValueError(
            f"{x.name!r} is not a ragged variable: declare it with "
            "layers.data(..., lod_level=1) or produce it with a sequence layer"
        )
    return ref


def _set_lod(var, lod_var):
    var._lod_ref = lod_var
    var.lod_level = 1
    return var


def _new_lod_var(helper, hint):
    return helper.create_variable_for_type_inference("int32", shape=(-1,))


def sequence_pool(input, pool_type="average"):
    helper = LayerHelper("sequence_pool")
    lod = _lod_of(input)
    out_shape = None
    if input.shape is not None:
        out_shape = (input.shape[0],) + tuple(input.shape[2:])
    out = helper.create_variable_for_type_inference(input.dtype, shape=out_shape)
    max_index = helper.create_variable_for_type_inference("int32", shape=out_shape)
    helper.append_op(
        "sequence_pool",
        inputs={"X": [input.name], "XLod": [lod.name]},
        outputs={"Out": [out.name], "MaxIndex": [max_index.name]},
        attrs={"pooltype": pool_type.upper()},
    )
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    lod = _lod_of(input)
    out = helper.create_variable_for_type_inference(input.dtype, shape=input.shape)
    helper.append_op(
        "sequence_softmax",
        inputs={"X": [input.name], "XLod": [lod.name]},
        outputs={"Out": [out.name]},
    )
    return _set_lod(out, lod)


def sequence_expand(x, y, ref_level=-1, name=None):
    """Broadcast one row of x per batch item across y's time axis, masked to
    y's lengths (reference sequence_expand with lod-level-0 x)."""
    helper = LayerHelper("sequence_expand", name=name)
    ylod = _lod_of(y)
    out_shape = None
    if x.shape is not None and y.shape is not None:
        feat = tuple(x.shape[1:]) if len(x.shape) == 2 or x.shape[1] != 1 else tuple(x.shape[2:])
        out_shape = (x.shape[0], y.shape[1]) + feat
    out = helper.create_variable_for_type_inference(x.dtype, shape=out_shape)
    helper.append_op(
        "sequence_expand",
        inputs={"X": [x.name], "Y": [y.name], "YLod": [ylod.name]},
        outputs={"Out": [out.name]},
        attrs={"ref_level": ref_level},
    )
    return _set_lod(out, ylod)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y, name=name)


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    lod = _lod_of(x)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(
        "sequence_reverse",
        inputs={"X": [x.name], "XLod": [lod.name]},
        outputs={"Out": [out.name]},
    )
    return _set_lod(out, lod)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Returns (padded dense tensor, lengths) like the reference (Out, Length)."""
    helper = LayerHelper("sequence_pad", name=name)
    lod = _lod_of(x)
    T = maxlen if maxlen is not None else (x.shape[1] if x.shape is not None else None)
    out_shape = None
    if x.shape is not None and T is not None and T > 0:
        out_shape = (x.shape[0], T) + tuple(x.shape[2:])
    out = helper.create_variable_for_type_inference(x.dtype, shape=out_shape)
    length = helper.create_variable_for_type_inference("int64", shape=(-1,))
    helper.append_op(
        "sequence_pad",
        inputs={"X": [x.name], "XLod": [lod.name], "PadValue": [pad_value.name]},
        outputs={"Out": [out.name], "Length": [length.name]},
        attrs={"padded_length": -1 if maxlen is None else int(maxlen)},
    )
    return out, length


def sequence_unpad(x, length, name=None):
    """Dense [b, T, *f] + lengths -> ragged variable."""
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    out_lod = _new_lod_var(helper, out.name)
    helper.append_op(
        "sequence_unpad",
        inputs={"X": [x.name], "Length": [length.name]},
        outputs={"Out": [out.name], "OutLod": [out_lod.name]},
    )
    return _set_lod(out, out_lod)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1, padding=True,
                  padding_start=None, bias_attr=None, param_attr=None, act=None, name=None):
    helper = LayerHelper("sequence_conv", name=name, act=act)
    lod = _lod_of(input)
    d = int(input.shape[-1])
    w = helper.create_parameter(param_attr, [filter_size * d, num_filters], input.dtype)
    out_shape = None
    if input.shape is not None:
        out_shape = tuple(input.shape[:2]) + (num_filters,)
    out = helper.create_variable_for_type_inference(input.dtype, shape=out_shape)
    helper.append_op(
        "sequence_conv",
        inputs={"X": [input.name], "XLod": [lod.name], "Filter": [w.name]},
        outputs={"Out": [out.name]},
        attrs={
            "contextStart": padding_start,
            "contextLength": filter_size,
            "contextStride": filter_stride,
        },
    )
    pre_act = helper.append_bias_op(out, bias_attr, [num_filters], dim_start=2)
    return _set_lod(helper.append_activation(pre_act), lod)


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    xs = list(input)
    lods = [_lod_of(x) for x in xs]
    T_out = None
    if all(x.shape is not None and x.shape[1] and x.shape[1] > 0 for x in xs):
        T_out = sum(int(x.shape[1]) for x in xs)
    out_shape = None
    if xs[0].shape is not None and T_out is not None:
        out_shape = (xs[0].shape[0], T_out) + tuple(xs[0].shape[2:])
    out = helper.create_variable_for_type_inference(xs[0].dtype, shape=out_shape)
    out_lod = _new_lod_var(helper, out.name)
    helper.append_op(
        "sequence_concat",
        inputs={"X": [x.name for x in xs], "XLod": [l.name for l in lods]},
        outputs={"Out": [out.name], "OutLod": [out_lod.name]},
    )
    return _set_lod(out, out_lod)


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    lod = _lod_of(input)
    out = helper.create_variable_for_type_inference(input.dtype, shape=input.shape)
    out_lod = _new_lod_var(helper, out.name)
    helper.append_op(
        "sequence_slice",
        inputs={"X": [input.name], "XLod": [lod.name],
                "Offset": [offset.name], "Length": [length.name]},
        outputs={"Out": [out.name], "OutLod": [out_lod.name]},
    )
    return _set_lod(out, out_lod)


def sequence_erase(input, tokens, name=None):
    helper = LayerHelper("sequence_erase", name=name)
    lod = _lod_of(input)
    out = helper.create_variable_for_type_inference(input.dtype, shape=input.shape)
    out_lod = _new_lod_var(helper, out.name)
    helper.append_op(
        "sequence_erase",
        inputs={"X": [input.name], "XLod": [lod.name]},
        outputs={"Out": [out.name], "OutLod": [out_lod.name]},
        attrs={"tokens": list(tokens)},
    )
    return _set_lod(out, out_lod)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    lod = _lod_of(input)
    out_shape = None
    if input.shape is not None:
        out_shape = tuple(input.shape[:2]) + (win_size,)
    out = helper.create_variable_for_type_inference(input.dtype, shape=out_shape)
    out_lod = _new_lod_var(helper, out.name)
    helper.append_op(
        "sequence_enumerate",
        inputs={"X": [input.name], "XLod": [lod.name]},
        outputs={"Out": [out.name], "OutLod": [out_lod.name]},
        attrs={"win_size": win_size, "pad_value": pad_value},
    )
    return _set_lod(out, out_lod)


def sequence_mask(x, maxlen, dtype="int64", name=None):
    """x holds lengths; out[i, t] = t < x[i] (reference sequence_mask op).
    maxlen must be a build-time int (static shapes under jit)."""
    helper = LayerHelper("sequence_mask", name=name)
    if maxlen is None or int(maxlen) <= 0:
        raise ValueError("sequence_mask needs a positive build-time maxlen on TPU")
    out = helper.create_variable_for_type_inference(dtype, shape=(-1, int(maxlen)))
    helper.append_op(
        "sequence_mask",
        inputs={"X": [x.name]},
        outputs={"Y": [out.name]},
        attrs={"maxlen": int(maxlen), "out_dtype": dtype},
    )
    return out


def attention_bias(q, k, causal=False, name=None):
    """Additive [b, 1, Tq, Tk] bias masking padded keys of ragged `k`
    (optionally causal); add it to pre-softmax attention scores."""
    helper = LayerHelper("attention_bias", name=name)
    klod = _lod_of(k)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "attention_bias",
        inputs={"Q": [q.name], "K": [k.name], "KLod": [klod.name]},
        outputs={"Out": [out.name]},
        attrs={"causal": causal},
    )
    return out


def position_encoding(x, name=None):
    """x + sinusoid positions along the (padded) time axis; preserves lod."""
    helper = LayerHelper("position_encoding", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(
        "position_encoding", inputs={"X": [x.name]}, outputs={"Out": [out.name]}
    )
    ref = getattr(x, "_lod_ref", None)
    return _set_lod(out, ref) if ref is not None else out


class DynamicRNN:
    """Reference `layers/control_flow.py:1692` — with-block RNN over ragged
    input.  The reference interprets the sub-block per time step over
    length-sorted shrinking batches; here the sub-block lowers to one
    `lax.scan` over the padded time axis with per-step masking
    (ops/sequence_ops.py `dynamic_rnn`), so the whole RNN is a single
    compiled XLA While with static shapes.

        drnn = layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(emb)         # [b, f] per step
            prev = drnn.memory(shape=[h])        # carried state
            hidden = layers.fc([word, prev], h, act="tanh")
            drnn.update_memory(prev, hidden)
            drnn.output(hidden)
        out = drnn()                             # ragged [b, T, h]
    """

    def __init__(self, name=None, is_reverse=False):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.main = default_main_program()
        self._steps = []      # (src Variable, sub Variable)
        self._mems = []       # dict(sub, init, shape, dtype, update)
        self._outputs = []    # sub Variables
        self._out_vars = None
        self._lod = None
        self._sub_block = None
        self.is_reverse = is_reverse
        self._allow_dense = False

    def block(self):
        return _DRNNGuard(self)

    def _require_in_block(self):
        if self._sub_block is None or self.main.current_block() is not self._sub_block:
            raise RuntimeError("call inside `with drnn.block():`")

    def step_input(self, x):
        self._require_in_block()
        lod = getattr(x, "_lod_ref", None) if self._allow_dense else _lod_of(x)
        if self._lod is None:
            self._lod = lod
        shape = None
        if x.shape is not None:
            shape = (x.shape[0],) + tuple(x.shape[2:])
        sub = self._sub_block.create_var(
            unique_name.generate("drnn.step"), shape=shape, dtype=x.dtype
        )
        self._steps.append((x, sub))
        return sub

    def static_input(self, x):
        # outer vars are visible inside the scan body via env capture
        self._require_in_block()
        return x

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        self._require_in_block()
        if init is not None:
            shape_full = init.shape
            dtype = init.dtype
        else:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            shape_full = (-1,) + tuple(int(s) for s in shape)
        sub = self._sub_block.create_var(
            unique_name.generate("drnn.mem"), shape=shape_full, dtype=dtype
        )
        self._mems.append(
            {"sub": sub, "init": init, "shape": shape, "dtype": str(dtype), "update": None,
             "value": value}
        )
        return sub

    def update_memory(self, mem, new):
        self._require_in_block()
        for m in self._mems:
            if m["sub"].name == mem.name:
                m["update"] = new
                return
        raise ValueError(f"{mem.name!r} is not a drnn memory")

    def output(self, *outputs):
        self._require_in_block()
        self._outputs.extend(outputs)

    def _finalize(self, parent_block, sub_idx):
        helper = self.helper
        if not self._steps:
            raise ValueError("DynamicRNN needs at least one step_input")
        for m in self._mems:
            if m["update"] is None:
                raise ValueError(f"memory {m['sub'].name!r} never updated")
        out_vars = []
        for o in self._outputs:
            shape = None
            src = self._steps[0][0]
            if o.shape is not None and src.shape is not None:
                shape = (src.shape[0], src.shape[1]) + tuple(o.shape[1:])
            ov = parent_block.create_var(
                unique_name.generate("drnn.out"), shape=shape, dtype=o.dtype
            )
            out_vars.append(ov)
        final_mems = []
        for m in self._mems:
            fv = parent_block.create_var(
                unique_name.generate("drnn.final_mem"),
                shape=m["sub"].shape,
                dtype=m["sub"].dtype,
            )
            final_mems.append(fv)
        inits = [m["init"] for m in self._mems if m["init"] is not None]
        rnn_inputs = {
            "X": [src.name for src, _ in self._steps],
            "MemInit": [v.name for v in inits],
        }
        if self._lod is not None:
            rnn_inputs["XLod"] = [self._lod.name]
        parent_block.append_op(
            "dynamic_rnn",
            inputs=rnn_inputs,
            outputs={
                "Out": [v.name for v in out_vars],
                "FinalMem": [v.name for v in final_mems],
            },
            attrs={
                "sub_block": sub_idx,
                "step_vars": [sub.name for _, sub in self._steps],
                "mem_vars": [m["sub"].name for m in self._mems],
                "mem_updates": [m["update"].name for m in self._mems],
                "out_vars": [o.name for o in self._outputs],
                "mem_has_init": [m["init"] is not None for m in self._mems],
                "mem_shapes": [list(m["shape"] or []) for m in self._mems],
                "mem_dtypes": [m["dtype"] for m in self._mems],
                "mem_values": [float(m["value"]) for m in self._mems],
                "is_reverse": self.is_reverse,
            },
        )
        if self._lod is not None:
            for ov in out_vars:
                _set_lod(ov, self._lod)
        self._out_vars = out_vars
        self._final_mems = final_mems

    def __call__(self):
        if self._out_vars is None:
            raise RuntimeError("DynamicRNN block not finished")
        return self._out_vars[0] if len(self._out_vars) == 1 else self._out_vars


class _DRNNGuard:
    def __init__(self, drnn: DynamicRNN):
        self.drnn = drnn
        self.main = drnn.main

    def __enter__(self):
        self.parent_block = self.main.current_block()
        self.drnn._sub_block = self.main.create_block()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.main.rollback()
            return False
        sub_idx = self.drnn._sub_block.idx
        self.main.rollback()
        self.drnn._finalize(self.parent_block, sub_idx)
        return False


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None, bias_attr=None,
                 use_peepholes=True, is_reverse=False, gate_activation="sigmoid",
                 cell_activation="tanh", candidate_activation="tanh",
                 dtype="float32", name=None):
    """Reference layers/nn.py:420 — `input` is the ragged pre-projected
    sequence [*, 4D]; returns (hidden, cell), both ragged [*, D].  Weight is
    the (D, 4D) hidden-hidden matrix {W_ch, W_ih, W_fh, W_oh}; bias is
    (1, 4D) or with peepholes (1, 7D) = {b, W_ic, W_fc, W_oc}."""
    if gate_activation != "sigmoid" or cell_activation != "tanh" or \
            candidate_activation != "tanh":
        raise NotImplementedError("dynamic_lstm: only the default activations")
    helper = LayerHelper("dynamic_lstm", name=name)
    hidden = size // 4
    lod = _lod_of(input)
    weight = helper.create_parameter(param_attr, [hidden, 4 * hidden], dtype)
    bias_size = [1, 7 * hidden] if use_peepholes else [1, 4 * hidden]
    bias = helper.create_parameter(bias_attr, bias_size, dtype, is_bias=True)
    shape = None
    if input.shape is not None:
        shape = (input.shape[0], input.shape[1], hidden)
    hidden_out = helper.create_variable_for_type_inference(dtype, shape=shape)
    cell_out = helper.create_variable_for_type_inference(dtype, shape=shape)
    inputs = {"Input": [input.name], "XLod": [lod.name], "Weight": [weight.name],
              "Bias": [bias.name]}
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    if c_0 is not None:
        inputs["C0"] = [c_0.name]
    helper.append_op(
        "dynamic_lstm", inputs=inputs,
        outputs={"Hidden": [hidden_out.name], "Cell": [cell_out.name]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse},
    )
    return _set_lod(hidden_out, lod), _set_lod(cell_out, lod)


def dynamic_gru(input, size, param_attr=None, bias_attr=None, is_reverse=False,
                gate_activation="sigmoid", candidate_activation="tanh",
                h_0=None, origin_mode=False, name=None):
    """Reference layers/nn.py dynamic_gru — `input` is ragged [*, 3D];
    returns ragged hidden [*, D]."""
    if gate_activation != "sigmoid" or candidate_activation != "tanh":
        raise NotImplementedError("dynamic_gru: only the default activations")
    helper = LayerHelper("dynamic_gru", name=name)
    lod = _lod_of(input)
    dtype = input.dtype
    weight = helper.create_parameter(param_attr, [size, 3 * size], dtype)
    bias = helper.create_parameter(bias_attr, [1, 3 * size], dtype, is_bias=True)
    shape = None
    if input.shape is not None:
        shape = (input.shape[0], input.shape[1], size)
    out = helper.create_variable_for_type_inference(dtype, shape=shape)
    inputs = {"Input": [input.name], "XLod": [lod.name], "Weight": [weight.name],
              "Bias": [bias.name]}
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    helper.append_op(
        "dynamic_gru", inputs=inputs, outputs={"Hidden": [out.name]},
        attrs={"is_reverse": is_reverse, "origin_mode": origin_mode},
    )
    return _set_lod(out, lod)


def warpctc(input, label, blank=0, norm_by_times=False):
    """CTC loss over ragged logits/labels (reference layers/nn.py warpctc).
    `input`: ragged [*, C] unnormalized logits; `label`: ragged [*, 1] int
    targets.  Returns [b, 1] per-sequence loss."""
    helper = LayerHelper("warpctc")
    in_lod = _lod_of(input)
    lbl_lod = _lod_of(label)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "warpctc",
        inputs={"Logits": [input.name], "XLod": [in_lod.name],
                "Label": [label.name], "LabelLod": [lbl_lod.name]},
        outputs={"Loss": [out.name]},
        attrs={"blank": blank, "norm_by_times": norm_by_times},
    )
    return out


class StaticRNN(DynamicRNN):
    """Fixed-length RNN over dense [b, T, f] inputs (reference
    layers/control_flow.py:278 StaticRNN — per-step sub-block, no length
    sorting).  Same with-block API as DynamicRNN; every row runs the full
    padded length (lengths companion optional)."""

    def __init__(self, name=None):
        super().__init__(name=name)
        self._allow_dense = True

    def step(self):
        """reference StaticRNN.step: alias of the with-block context."""
        return self.block()

    def step_output(self, o):
        """reference StaticRNN.step_output: single-output form of output()."""
        return self.output(o)


def linear_chain_crf(input, label, param_attr=None):
    """CRF negative log-likelihood layer (reference layers/nn.py
    linear_chain_crf).  `input`: ragged [*, D] unnormalized tag scores;
    `label`: ragged [*, 1] int tags.  Creates the [D+2, D] transition
    parameter (rows 0/1 = start/end weights) and returns the per-sequence
    [b, 1] cost.  Share the parameter with crf_decoding via a named
    ParamAttr (reference convention: name="crfw")."""
    helper = LayerHelper("linear_chain_crf")
    in_lod = _lod_of(input)
    lbl_lod = _lod_of(label)
    tag_num = int(input.shape[-1])
    w = helper.create_parameter(param_attr, [tag_num + 2, tag_num], "float32")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "linear_chain_crf",
        inputs={"Emission": [input.name], "XLod": [in_lod.name],
                "Transition": [w.name],
                "Label": [label.name], "LabelLod": [lbl_lod.name]},
        outputs={"LogLikelihood": [out.name]},
    )
    return out


def crf_decoding(input, param_attr, label=None):
    """Viterbi decode layer (reference layers/nn.py crf_decoding).  Reuses
    the transition parameter trained by linear_chain_crf (same named
    ParamAttr).  Without `label`: [b, T] int64 best tag paths (0 past each
    row's length).  With `label`: per-position 0/1 correctness indicator."""
    helper = LayerHelper("crf_decoding")
    in_lod = _lod_of(input)
    tag_num = int(input.shape[-1])
    w = helper.create_parameter(param_attr, [tag_num + 2, tag_num], "float32")
    out = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": [input.name], "XLod": [in_lod.name],
              "Transition": [w.name]}
    if label is not None:
        inputs["Label"] = [label.name]
    helper.append_op("crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [out.name]})
    _set_lod(out, in_lod)
    return out


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """Projection LSTM (reference layers/nn.py dynamic_lstmp over
    lstmp_op.cc).  `input` ragged [*, 4D]; returns (projection [*, P],
    cell [*, D]); the projection activation (reference default 'tanh',
    lstmp_op.h) is applied to h @ W_proj inside the recurrence."""
    if gate_activation != "sigmoid" or cell_activation != "tanh" or \
            candidate_activation != "tanh":
        raise NotImplementedError("dynamic_lstmp: only the default activations")
    if proj_activation not in ("tanh", "sigmoid", "relu", "identity"):
        raise NotImplementedError(
            f"dynamic_lstmp: proj_activation {proj_activation!r}")
    helper = LayerHelper("dynamic_lstmp", name=name)
    hidden = size // 4
    lod = _lod_of(input)
    weight = helper.create_parameter(param_attr, [proj_size, 4 * hidden], dtype)
    proj_weight = helper.create_parameter(param_attr, [hidden, proj_size], dtype)
    bias_size = [1, 7 * hidden] if use_peepholes else [1, 4 * hidden]
    bias = helper.create_parameter(bias_attr, bias_size, dtype, is_bias=True)
    pshape = cshape = None
    if input.shape is not None:
        pshape = (input.shape[0], input.shape[1], proj_size)
        cshape = (input.shape[0], input.shape[1], hidden)
    proj_out = helper.create_variable_for_type_inference(dtype, shape=pshape)
    cell_out = helper.create_variable_for_type_inference(dtype, shape=cshape)
    helper.append_op(
        "dynamic_lstmp",
        inputs={"Input": [input.name], "XLod": [lod.name],
                "Weight": [weight.name], "ProjWeight": [proj_weight.name],
                "Bias": [bias.name]},
        outputs={"Projection": [proj_out.name], "Cell": [cell_out.name]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "proj_activation": proj_activation},
    )
    _set_lod(proj_out, lod)
    _set_lod(cell_out, lod)
    return proj_out, cell_out


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Multi-layer LSTM over dense [b, T, I] input (reference layers/nn.py
    lstm over cudnn_lstm_op).  Returns (rnn_out [b, T, D*dirs],
    last_h [L*dirs, b, D], last_c [L*dirs, b, D]).  The flat weight layout
    is documented in the cudnn_lstm lowering (per layer+direction:
    Wx, Wh, bx, bh; gates i,f,c,o)."""
    helper = LayerHelper("lstm", name=name)
    dirs = 2 if is_bidirec else 1
    I = int(input.shape[-1])
    D = hidden_size
    total = 0
    for layer in range(num_layers):
        in_dim = I if layer == 0 else D * dirs
        total += dirs * (4 * D * in_dim + 4 * D * D + 8 * D)
    w = helper.create_parameter(None, [total], input.dtype,
                                default_initializer=default_initializer)
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    last_c = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "cudnn_lstm",
        inputs={"Input": [input.name], "W": [w.name],
                "InitH": [init_h.name], "InitC": [init_c.name]},
        outputs={"Out": [out.name], "LastH": [last_h.name],
                 "LastC": [last_c.name]},
        attrs={"hidden_size": hidden_size, "num_layers": num_layers,
               "is_bidirec": is_bidirec, "dropout_prob": dropout_prob,
               "is_test": is_test},
    )
    return out, last_h, last_c


def sequence_scatter(input, index, updates, name=None):
    """reference layers/nn.py sequence_scatter: add ragged per-row updates
    into the dense input at ragged column indices."""
    helper = LayerHelper("sequence_scatter", name=name)
    idx_lod = _lod_of(index)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "sequence_scatter",
        inputs={"X": [input.name], "Ids": [index.name],
                "IdsLod": [idx_lod.name], "Updates": [updates.name]},
        outputs={"Out": [out.name]},
    )
    return out
