"""NN layers (reference: python/paddle/fluid/layers/nn.py — fc:213,
conv2d:1991, batch_norm:3036, etc.).  Builders only: each appends program
ops; all numerics live in ops/ lowerings."""
from __future__ import annotations

import numpy as np

from ..core.dtypes import canonical_dtype
from ..core.layer_helper import LayerHelper
from ..core.program import Variable


def _out(helper, dtype, shape=None):
    return helper.create_variable_for_type_inference(dtype, shape=shape)


def _keep_lod(src, out):
    """Propagate the ragged lengths companion through a layer whose output
    keeps the time axis (dropout/scale/embedding/layer_norm/...), so model
    code doesn't hand-thread `_lod_ref` (paddle_tpu/lod.py)."""
    ref = getattr(src, "_lod_ref", None)
    if ref is not None:
        out._lod_ref = ref
        out.lod_level = 1
    return out


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("fc", name=name, act=act)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for inp in inputs:
        in_shape = inp.shape
        fan_in = int(np.prod(in_shape[num_flatten_dims:]))
        w = helper.create_parameter(param_attr, [fan_in, size], inp.dtype)
        out = _out(helper, inp.dtype, shape=tuple(in_shape[:num_flatten_dims]) + (size,))
        helper.append_op(
            "mul",
            inputs={"X": [inp.name], "Y": [w.name]},
            outputs={"Out": [out.name]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(out)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = _out(helper, mul_results[0].dtype, shape=mul_results[0].shape)
        helper.append_op(
            "sum", inputs={"X": [v.name for v in mul_results]}, outputs={"Out": [pre_bias.name]}
        )
    pre_act = helper.append_bias_op(pre_bias, bias_attr, [size], dim_start=num_flatten_dims)
    out = helper.append_activation(pre_act)
    # time-axis-preserving projection keeps the ragged lengths companion
    return _keep_lod(inputs[0], out) if num_flatten_dims >= 2 else out


def embedding(input, size, is_sparse=False, is_distributed=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    helper = LayerHelper("embedding")
    w = helper.create_parameter(param_attr, size, dtype)
    in_shape = input.shape
    out_shape = None
    if in_shape is not None:
        base = in_shape[:-1] if in_shape[-1] == 1 else in_shape
        out_shape = tuple(base) + (size[1],)
    out = _out(helper, dtype, shape=out_shape)
    helper.append_op(
        "lookup_table",
        inputs={"Ids": [input.name], "W": [w.name]},
        outputs={"Out": [out.name]},
        attrs={
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
            "padding_idx": padding_idx,
        },
    )
    return _keep_lod(input, out)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=None,
           param_attr=None, bias_attr=None, use_cudnn=True, act=None, name=None,
           data_format="NCHW"):
    helper = LayerHelper("conv2d", name=name, act=act)
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"conv2d: data_format must be NCHW or NHWC, got {data_format!r}")
    ch_axis = 1 if data_format == "NCHW" else 3
    num_channels = input.shape[ch_axis]
    # filter stays OIHW in both layouts so params are layout-independent
    filter_shape = [num_filters, num_channels // groups, filter_size[0], filter_size[1]]
    from ..core.initializer import NormalInitializer

    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    default_init = NormalInitializer(0.0, float(np.sqrt(2.0 / fan_in)))
    w = helper.create_parameter(param_attr, filter_shape, input.dtype, default_initializer=default_init)
    out_shape = None
    h_axis, w_axis = (2, 3) if data_format == "NCHW" else (1, 2)
    # padding may be [ph, pw] (symmetric) or [top, bottom, left, right]
    pad_hw = ((padding[0], padding[1]), (padding[2], padding[3])) \
        if len(padding) == 4 else ((padding[0], padding[0]), (padding[1], padding[1]))
    if input.shape is not None and input.shape[h_axis] is not None:
        def _osz(i, k, p2, s, d):
            if i is None or i < 0:
                return -1
            return (i + p2[0] + p2[1] - (d * (k - 1) + 1)) // s + 1
        oh = _osz(input.shape[h_axis], filter_size[0], pad_hw[0], stride[0], dilation[0])
        ow = _osz(input.shape[w_axis], filter_size[1], pad_hw[1], stride[1], dilation[1])
        if data_format == "NCHW":
            out_shape = (input.shape[0], num_filters, oh, ow)
        else:
            out_shape = (input.shape[0], oh, ow, num_filters)
    pre_bias = _out(helper, input.dtype, shape=out_shape)
    helper.append_op(
        "conv2d",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, bias_attr, [num_filters], dim_start=ch_axis)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None, stride=1, padding=0,
                     dilation=1, groups=None, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", name=name, act=act)
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    num_channels = input.shape[1]
    filter_shape = [num_channels, num_filters // groups, filter_size[0], filter_size[1]]
    w = helper.create_parameter(param_attr, filter_shape, input.dtype)
    pre_bias = _out(helper, input.dtype)
    helper.append_op(
        "conv2d_transpose",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation, "groups": groups},
    )
    pre_act = helper.append_bias_op(pre_bias, bias_attr, [num_filters], dim_start=1)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, exclusive=True, name=None,
           data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride, pool_stride]
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding, pool_padding]
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"pool2d: data_format must be NCHW or NHWC, got {data_format!r}")
    h_axis, w_axis = (2, 3) if data_format == "NCHW" else (1, 2)
    out_shape = None
    if input.shape is not None and not global_pooling:
        def _osz(i, k, p, s):
            if i is None or i < 0:
                return -1
            return (i + 2 * p - k) // s + 1
        oh = _osz(input.shape[h_axis], pool_size[0], pool_padding[0], pool_stride[0])
        ow = _osz(input.shape[w_axis], pool_size[1], pool_padding[1], pool_stride[1])
        if data_format == "NCHW":
            out_shape = (input.shape[0], input.shape[1], oh, ow)
        else:
            out_shape = (input.shape[0], oh, ow, input.shape[3])
    elif input.shape is not None:
        if data_format == "NCHW":
            out_shape = (input.shape[0], input.shape[1], 1, 1)
        else:
            out_shape = (input.shape[0], 1, 1, input.shape[3])
    out = _out(helper, input.dtype, shape=out_shape)
    helper.append_op(
        "pool2d",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "pooling_type": pool_type,
            "ksize": pool_size,
            "strides": pool_stride,
            "paddings": pool_padding,
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", name=None, moving_mean_name=None,
               moving_variance_name=None, use_global_stats=False):
    helper = LayerHelper("batch_norm", name=name, act=act)
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = input.dtype
    # norm params and running stats stay fp32 even for bf16/fp16 activations
    # (reference batch_norm_op.cc keeps fp32 scale/bias for fp16 kernels);
    # the lowering normalizes in fp32 and casts Y back to the input dtype
    param_dtype = "float32" if str(dtype) in ("bfloat16", "float16") else dtype
    from ..core.initializer import ConstantInitializer
    from ..core.param_attr import ParamAttr

    scale = helper.create_parameter(param_attr, [ch], param_dtype, default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, [ch], param_dtype, is_bias=True)
    # moving stats: persistable, not trainable
    mean_attr = ParamAttr(name=moving_mean_name, initializer=ConstantInitializer(0.0), trainable=False)
    var_attr = ParamAttr(name=moving_variance_name, initializer=ConstantInitializer(1.0), trainable=False)
    mean = helper.create_parameter(mean_attr, [ch], param_dtype)
    variance = helper.create_parameter(var_attr, [ch], param_dtype)
    mean.stop_gradient = True
    variance.stop_gradient = True

    saved_mean = _out(helper, dtype, shape=(ch,))
    saved_var = _out(helper, dtype, shape=(ch,))
    out = _out(helper, dtype, shape=input.shape)
    helper.append_op(
        "batch_norm",
        inputs={
            "X": [input.name],
            "Scale": [scale.name],
            "Bias": [bias.name],
            "Mean": [mean.name],
            "Variance": [variance.name],
        },
        outputs={
            "Y": [out.name],
            "MeanOut": [mean.name],
            "VarianceOut": [variance.name],
            "SavedMean": [saved_mean.name],
            "SavedVariance": [saved_var.name],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", name=name, act=act)
    dtype = input.dtype
    norm_size = int(np.prod(input.shape[begin_norm_axis:]))
    inputs = {"X": [input.name]}
    from ..core.initializer import ConstantInitializer

    if scale:
        s = helper.create_parameter(param_attr, [norm_size], dtype, default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(bias_attr, [norm_size], dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    out = _out(helper, dtype, shape=input.shape)
    mean = _out(helper, dtype)
    var = _out(helper, dtype)
    helper.append_op(
        "layer_norm",
        inputs=inputs,
        outputs={"Y": [out.name], "Mean": [mean.name], "Variance": [var.name]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return _keep_lod(input, helper.append_activation(out))


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    mask = _out(helper, x.dtype, shape=x.shape)
    helper.append_op(
        "dropout",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "Mask": [mask.name]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "fix_seed": seed is not None,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return _keep_lod(x, out)


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = _out(helper, input.dtype, shape=input.shape)
    helper.append_op(
        "softmax", inputs={"X": [input.name]}, outputs={"Out": [out.name]}, attrs={"axis": axis}
    )
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    shape = None
    if input.shape is not None:
        shape = tuple(input.shape[:-1]) + (1,)
    out = _out(helper, input.dtype, shape=shape)
    helper.append_op(
        "cross_entropy",
        inputs={"X": [input.name], "Label": [label.name]},
        outputs={"Y": [out.name]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    loss_shape = tuple(logits.shape[:-1]) + (1,) if logits.shape is not None else None
    softmax_out = _out(helper, logits.dtype, shape=logits.shape)
    loss = _out(helper, logits.dtype, shape=loss_shape)
    helper.append_op(
        "softmax_with_cross_entropy",
        inputs={"Logits": [logits.name], "Label": [label.name]},
        outputs={"Loss": [loss.name], "Softmax": [softmax_out.name]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    _keep_lod(logits, loss)
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None, normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        inputs={"X": [x.name], "Label": [label.name]},
        outputs={"Out": [out.name]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = _out(helper, input.dtype, shape=input.shape)
    helper.append_op(
        "square_error_cost",
        inputs={"X": [input.name], "Y": [label.name]},
        outputs={"Out": [out.name]},
    )
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = _out(helper, x.dtype, shape=(1,))
    helper.append_op("mean", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    helper = LayerHelper("mul")
    out = _out(helper, x.dtype)
    helper.append_op(
        "mul",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = _out(helper, x.dtype)
    helper.append_op(
        "matmul",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": float(alpha)},
    )
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name)
    out_shape = []
    for i, s in enumerate(shape):
        if s == 0:
            out_shape.append(x.shape[i] if x.shape is not None else -1)
        else:
            out_shape.append(s)
    out = _out(helper, x.dtype, shape=tuple(out_shape))
    xshape = _out(helper, x.dtype)
    helper.append_op(
        "reshape2",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "XShape": [xshape.name]},
        attrs={"shape": list(shape)},
    )
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    shape = tuple(x.shape[p] for p in perm) if x.shape is not None else None
    out = _out(helper, x.dtype, shape=shape)
    xshape = _out(helper, x.dtype)
    helper.append_op(
        "transpose2",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "XShape": [xshape.name]},
        attrs={"axis": list(perm)},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else len(input.shape) + dim
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "sections": [], "axis": dim}
    else:
        n = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    outs = [_out(helper, input.dtype) for _ in range(n)]
    helper.append_op(
        "split", inputs={"X": [input.name]}, outputs={"Out": [o.name for o in outs]}, attrs=attrs
    )
    return outs


def _reduce_layer(op_type):
    def f(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = _out(helper, input.dtype)
        if dim is None:
            attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
        else:
            attrs = {
                "dim": [dim] if isinstance(dim, int) else list(dim),
                "keep_dim": keep_dim,
                "reduce_all": False,
            }
        helper.append_op(op_type, inputs={"X": [input.name]}, outputs={"Out": [out.name]}, attrs=attrs)
        return out

    f.__name__ = op_type
    return f


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    shape = tuple(input.shape[:-1]) + (k,) if input.shape is not None else None
    values = _out(helper, input.dtype, shape=shape)
    indices = _out(helper, "int64", shape=shape)
    helper.append_op(
        "top_k",
        inputs={"X": [input.name]},
        outputs={"Out": [values.name], "Indices": [indices.name]},
        attrs={"k": k},
    )
    return values, indices


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = _out(helper, "float32")
    helper.append_op(
        "one_hot", inputs={"X": [input.name]}, outputs={"Out": [out.name]}, attrs={"depth": depth}
    )
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op(
        "clip", inputs={"X": [x.name]}, outputs={"Out": [out.name]}, attrs={"min": min, "max": max}
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op(
        "clip_by_norm",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"max_norm": max_norm},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = _out(helper, dtype, shape=label.shape)
    inputs = {"X": [label.name]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist.name]
    helper.append_op(
        "label_smooth", inputs=inputs, outputs={"Out": [out.name]}, attrs={"epsilon": float(epsilon)}
    )
    return out


def _elementwise_layer(op_type):
    def f(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name, act=act)
        out = _out(helper, x.dtype, shape=x.shape)
        helper.append_op(
            op_type,
            inputs={"X": [x.name], "Y": [y.name]},
            outputs={"Out": [out.name]},
            attrs={"axis": axis},
        )
        return _keep_lod(x, helper.append_activation(out))

    f.__name__ = op_type
    return f


elementwise_add = _elementwise_layer("elementwise_add")
elementwise_sub = _elementwise_layer("elementwise_sub")
elementwise_mul = _elementwise_layer("elementwise_mul")
elementwise_div = _elementwise_layer("elementwise_div")
elementwise_max = _elementwise_layer("elementwise_max")
elementwise_min = _elementwise_layer("elementwise_min")
elementwise_pow = _elementwise_layer("elementwise_pow")


def _act_layer(op_type):
    def f(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = _out(helper, x.dtype, shape=x.shape)
        helper.append_op(op_type, inputs={"X": [x.name]}, outputs={"Out": [out.name]})
        return out

    f.__name__ = op_type
    return f


relu = _act_layer("relu")
relu6 = _act_layer("relu6")
sigmoid = _act_layer("sigmoid")
logsigmoid = _act_layer("logsigmoid")
tanh = _act_layer("tanh")
exp = _act_layer("exp")
log = _act_layer("log")
sqrt = _act_layer("sqrt")
abs = _act_layer("abs")
square = _act_layer("square")
softplus = _act_layer("softplus")
softsign = _act_layer("softsign")
gelu = _act_layer("gelu")
erf = _act_layer("erf")
floor = _act_layer("floor")
ceil = _act_layer("ceil")
round = _act_layer("round")
reciprocal = _act_layer("reciprocal")
sin = _act_layer("sin")
cos = _act_layer("cos")


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op(
        "leaky_relu", inputs={"X": [x.name]}, outputs={"Out": [out.name]}, attrs={"alpha": alpha}
    )
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op(
        "scale",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"scale": float(scale), "bias": float(bias), "bias_after_scale": bias_after_scale},
    )
    out = helper.append_activation(out)
    _keep_lod(x, out)
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op(
        "pow", inputs={"X": [x.name]}, outputs={"Out": [out.name]}, attrs={"factor": float(factor)}
    )
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = _out(helper, input.dtype)
    xshape = _out(helper, input.dtype)
    helper.append_op(
        "squeeze2",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name], "XShape": [xshape.name]},
        attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = _out(helper, input.dtype)
    xshape = _out(helper, input.dtype)
    helper.append_op(
        "unsqueeze2",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name], "XShape": [xshape.name]},
        attrs={"axes": list(axes)},
    )
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = _out(helper, xs[0].dtype)
    helper.append_op(
        "stack", inputs={"X": [v.name for v in xs]}, outputs={"Y": [out.name]}, attrs={"axis": axis}
    )
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    shape = None
    if input.shape is not None:
        shape = list(input.shape)
        for ax, st, en in zip(axes, starts, ends):
            dim = shape[ax]
            if dim is None or dim < 0:
                continue
            st2 = max(st + dim, 0) if st < 0 else min(st, dim)
            en2 = max(en + dim, 0) if en < 0 else min(en, dim)
            shape[ax] = max(en2 - st2, 0)
        shape = tuple(shape)
    out = _out(helper, input.dtype, shape=shape)
    helper.append_op(
        "slice",
        inputs={"Input": [input.name]},
        outputs={"Out": [out.name]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def ring_attention(q, k, v, causal=False, sp_axis="sp", batch_axis="dp", name=None):
    """Sequence-parallel attention over (B, H, L, dh) tensors; L shards over
    the `sp` mesh axis when the program runs on a mesh carrying it (new
    capability vs the reference — SURVEY.md §5.7)."""
    helper = LayerHelper("ring_attention", name=name)
    out = _out(helper, q.dtype, shape=q.shape)
    helper.append_op(
        "ring_attention",
        inputs={"Q": [q.name], "K": [k.name], "V": [v.name]},
        outputs={"Out": [out.name]},
        attrs={"causal": causal, "sp_axis": sp_axis, "batch_axis": batch_axis},
    )
    return out


def space_to_depth(x, blocksize, name=None):
    """reference layers/nn.py:10411 space_to_depth over space_to_depth_op:
    [B, C, H, W] -> [B, C*bs^2, H/bs, W/bs] (C must divide bs^2 — the
    reference InferShape enforces this quirk)."""
    helper = LayerHelper("space_to_depth", name=name)
    bs = int(blocksize)
    shape = None
    if x.shape is not None and None not in x.shape[1:]:
        b, c, h, w = x.shape
        shape = (b, c * bs * bs, h // bs, w // bs)
    out = _out(helper, x.dtype, shape=shape)
    helper.append_op("space_to_depth", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"blocksize": bs})
    return out


def fused_attention(q, k, v, bias=None, causal=False, scale=None,
                    score_dtype=None, name=None):
    """Fused scaled-dot-product attention over (B, H, L, dh) tensors.

    Long sequences lower to the streaming flash kernel (score matrix never
    materialized in HBM, fwd + bwd); moderate lengths use the mixed-
    precision XLA formulation.  `bias` is an additive pre-softmax mask,
    (B, 1|H, Lq, Lk).  `scale` defaults to 1/sqrt(dh).
    `score_dtype="bfloat16"` materializes the score tensor in bf16 (half
    the attention HBM traffic; pre-softmax logits quantized to 8 mantissa
    bits — softmax reductions stay f32)."""
    helper = LayerHelper("fused_attention", name=name)
    out = _out(helper, q.dtype, shape=q.shape)
    inputs = {"Q": [q.name], "K": [k.name], "V": [v.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    attrs = {"causal": causal}
    if scale is not None:
        attrs["scale"] = float(scale)
    if score_dtype is not None:
        sd = {"bf16": "bfloat16", "bfloat16": "bfloat16",
              "float32": "float32", "fp32": "float32"}.get(str(score_dtype))
        if sd is None:
            raise ValueError(
                f"fused_attention: score_dtype must be 'float32' or "
                f"'bfloat16', got {score_dtype!r}")
        attrs["score_dtype"] = sd
    helper.append_op("fused_attention", inputs=inputs, outputs={"Out": [out.name]}, attrs=attrs)
    return out


def dropout_prob_check(p):
    if not 0 <= p < 1:
        raise ValueError("dropout prob must be in [0,1)")


def resize_bilinear(input, out_shape=None, scale=None, name=None, align_corners=True):
    """reference nn.py resize_bilinear over bilinear_interp_op."""
    helper = LayerHelper("bilinear_interp", name=name)
    attrs = {"align_corners": align_corners}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
        oshape = None
        if input.shape is not None:
            oshape = (input.shape[0], input.shape[1], attrs["out_h"], attrs["out_w"])
    else:
        attrs["scale"] = float(scale)
        oshape = None
    out = _out(helper, input.dtype, shape=oshape)
    helper.append_op("bilinear_interp", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    return out


def resize_nearest(input, out_shape=None, scale=None, name=None, align_corners=True):
    helper = LayerHelper("nearest_interp", name=name)
    attrs = {"align_corners": align_corners}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    else:
        attrs["scale"] = float(scale)
    out = _out(helper, input.dtype)
    helper.append_op("nearest_interp", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0, name=None):
    helper = LayerHelper("pad2d", name=name)
    out = _out(helper, input.dtype)
    helper.append_op("pad2d", inputs={"X": [input.name]}, outputs={"Out": [out.name]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": pad_value})
    return out


def crop(x, shape=None, offsets=None, name=None):
    if shape is None:
        raise ValueError("crop: `shape` is required (static output extents)")
    helper = LayerHelper("crop", name=name)
    out = _out(helper, x.dtype, shape=tuple(shape) if shape else None)
    helper.append_op("crop", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"offsets": list(offsets or [0] * len(shape)),
                            "shape": list(shape)})
    return out


def Print(input, first_n=-1, message=None, summarize=-1, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """reference layers.Print (print_op.cc): identity that prints at
    execution (host callback through jax.debug.print)."""
    helper = LayerHelper("print")
    out = _out(helper, input.dtype, shape=input.shape)
    msg = message or f"{input.name}: " if print_tensor_name else (message or "")
    helper.append_op("print", inputs={"X": [input.name]}, outputs={"Out": [out.name]},
                     attrs={"message": msg, "first_n": first_n})
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", name=name, act=act)
    if data_layout != "NCHW":
        raise NotImplementedError("group_norm: only NCHW")
    c = input.shape[1]
    from ..core.initializer import ConstantInitializer

    scale = helper.create_parameter(param_attr, [c], input.dtype,
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, [c], input.dtype, is_bias=True)
    out = _out(helper, input.dtype, shape=input.shape)
    mean = _out(helper, "float32")
    var = _out(helper, "float32")
    helper.append_op(
        "group_norm",
        inputs={"X": [input.name], "Scale": [scale.name], "Bias": [bias.name]},
        outputs={"Y": [out.name], "Mean": [mean.name], "Variance": [var.name]},
        attrs={"epsilon": epsilon, "groups": groups},
    )
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("instance_norm", name=name)
    c = input.shape[1]
    from ..core.initializer import ConstantInitializer

    scale = helper.create_parameter(param_attr, [c], input.dtype,
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, [c], input.dtype, is_bias=True)
    out = _out(helper, input.dtype, shape=input.shape)
    smean = _out(helper, "float32")
    svar = _out(helper, "float32")
    helper.append_op(
        "instance_norm",
        inputs={"X": [input.name], "Scale": [scale.name], "Bias": [bias.name]},
        outputs={"Y": [out.name], "SavedMean": [smean.name],
                 "SavedVariance": [svar.name]},
        attrs={"epsilon": epsilon},
    )
    return out


def l2_normalize(x, axis, epsilon=1e-10, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    norm = _out(helper, x.dtype)
    helper.append_op("norm", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Norm": [norm.name]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = _out(helper, input.dtype)
    helper.append_op("gather_nd", inputs={"X": [input.name], "Index": [index.name]},
                     outputs={"Out": [out.name]})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = _out(helper, input.dtype, shape=input.shape)
    helper.append_op("scatter",
                     inputs={"X": [input.name], "Ids": [index.name],
                             "Updates": [updates.name]},
                     outputs={"Out": [out.name]}, attrs={"overwrite": overwrite})
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    helper = LayerHelper("cumsum", name=name)
    out = _out(helper, x.dtype, shape=x.shape)
    helper.append_op("cumsum", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"axis": axis, "exclusive": exclusive, "reverse": reverse})
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = _out(helper, input.dtype, shape=input.shape)
    ids = _out(helper, "int64", shape=input.shape)
    helper.append_op("argsort", inputs={"X": [input.name]},
                     outputs={"Out": [out.name], "Indices": [ids.name]},
                     attrs={"axis": axis, "descending": descending})
    return out, ids


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    out = _out(helper, x.dtype)
    helper.append_op("flatten2", inputs={"X": [x.name]},
                     outputs={"Out": [out.name],
                              "XShape": [_out(helper, x.dtype).name]},
                     attrs={"axis": axis})
    return out


def gather(input, index, name=None):
    """rows of input at index (reference layers.gather over gather_op)."""
    helper = LayerHelper("gather", name=name)
    out = _out(helper, input.dtype)
    helper.append_op("gather", inputs={"X": [input.name], "Index": [index.name]},
                     outputs={"Out": [out.name]})
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Embed a host python callable in the program (reference layers.py_func
    over py_func_op.cc).  `out` declares the output variables (shapes/dtypes
    must be exact — XLA needs them static); backward_func is not supported
    (the callback is opaque to autodiff; stop-gradient semantics)."""
    from ..ops.control_flow_ops import register_py_func

    if backward_func is not None:
        raise NotImplementedError(
            "py_func: backward_func is not supported — the host callback is "
            "opaque to the vjp; compute gradients with program ops instead")
    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        if o.shape is None or any(s is None or s < 0 for s in o.shape):
            raise ValueError(
                f"py_func: output {o.name!r} needs a fully static shape")
    fid = register_py_func(func)
    helper.append_op(
        "py_func",
        inputs={"X": [v.name for v in xs]},
        outputs={"Out": [o.name for o in outs]},
        attrs={"func_id": fid,
               "out_shapes": [list(o.shape) for o in outs],
               "out_dtypes": [str(o.dtype) for o in outs]},
    )
    return out
