"""Metric layers (reference: python/paddle/fluid/layers/metric_op.py)."""
from __future__ import annotations

from ..core.layer_helper import LayerHelper
from . import nn


def accuracy(input, label, k=1, correct=None, total=None):
    """top-k accuracy (reference: metric_op.py accuracy:30)."""
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = nn.topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32", shape=(1,))
    if correct is None:
        correct = helper.create_variable_for_type_inference("int32", shape=(1,))
    if total is None:
        total = helper.create_variable_for_type_inference("int32", shape=(1,))
    helper.append_op(
        "accuracy",
        inputs={"Out": [topk_out.name], "Indices": [topk_indices.name], "Label": [label.name]},
        outputs={"Accuracy": [acc_out.name], "Correct": [correct.name], "Total": [total.name]},
    )
    return acc_out


def mean_iou(input, label, num_classes):
    raise NotImplementedError("mean_iou: pending detection batch")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    raise NotImplementedError("auc: pending metrics batch")
