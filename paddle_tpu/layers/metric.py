"""Metric layers (reference: python/paddle/fluid/layers/metric_op.py)."""
from __future__ import annotations

from ..core.layer_helper import LayerHelper
from ..core.initializer import ConstantInitializer
from ..core.param_attr import ParamAttr
from . import nn


def accuracy(input, label, k=1, correct=None, total=None):
    """top-k accuracy (reference: metric_op.py accuracy:30)."""
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = nn.topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32", shape=(1,))
    if correct is None:
        correct = helper.create_variable_for_type_inference("int32", shape=(1,))
    if total is None:
        total = helper.create_variable_for_type_inference("int32", shape=(1,))
    helper.append_op(
        "accuracy",
        inputs={"Out": [topk_out.name], "Indices": [topk_indices.name], "Label": [label.name]},
        outputs={"Accuracy": [acc_out.name], "Correct": [correct.name], "Total": [total.name]},
    )
    return acc_out


def mean_iou(input, label, num_classes):
    """Mean Intersection-over-Union (reference metric_op.py mean_iou /
    operators/metrics/mean_iou_op).  Returns (mean_iou [1], out_wrong [C],
    out_correct [C])."""
    helper = LayerHelper("mean_iou")
    iou = helper.create_variable_for_type_inference("float32", shape=(1,))
    wrong = helper.create_variable_for_type_inference("int32", shape=(num_classes,))
    correct = helper.create_variable_for_type_inference("int32", shape=(num_classes,))
    helper.append_op(
        "mean_iou",
        inputs={"Predictions": [input.name], "Labels": [label.name]},
        outputs={"OutMeanIou": [iou.name], "OutWrong": [wrong.name],
                 "OutCorrect": [correct.name]},
        attrs={"num_classes": num_classes},
    )
    return iou, wrong, correct


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    """Streaming ROC-AUC (reference metric_op.py auc / operators/metrics/
    auc_op.cc): persistable positive/negative histograms bucketed by
    predicted probability accumulate across batches; AUC is the trapezoid
    integral over thresholds.  Returns (auc_out, [batch stats unsupported —
    single global accumulator, the reference's slide_steps=0 mode])."""
    if curve != "ROC":
        raise NotImplementedError("auc: only curve='ROC'")
    helper = LayerHelper("auc")
    stat_pos = helper.create_parameter(
        ParamAttr(name=helper.name + ".stat_pos", trainable=False,
                  initializer=ConstantInitializer(0.0)),
        [num_thresholds + 1], "int64")
    stat_neg = helper.create_parameter(
        ParamAttr(name=helper.name + ".stat_neg", trainable=False,
                  initializer=ConstantInitializer(0.0)),
        [num_thresholds + 1], "int64")
    stat_pos.stop_gradient = True
    stat_neg.stop_gradient = True
    auc_out = helper.create_variable_for_type_inference("float32", shape=(1,))
    helper.append_op(
        "auc",
        inputs={"Predict": [input.name], "Label": [label.name],
                "StatPos": [stat_pos.name], "StatNeg": [stat_neg.name]},
        outputs={"AUC": [auc_out.name], "StatPosOut": [stat_pos.name],
                 "StatNegOut": [stat_neg.name]},
        attrs={"num_thresholds": num_thresholds},
    )
    return auc_out
