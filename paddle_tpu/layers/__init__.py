"""fluid.layers-compatible namespace."""
import functools as _functools

from .control_flow import (  # noqa: F401
    While,
    array_length,
    array_read,
    array_write,
    cond,
    create_array,
    equal,
    greater_than,
    increment,
    less_than,
)
from .io import data  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    cosine_decay,
    exponential_decay,
    inverse_time_decay,
    linear_lr_warmup,
    natural_exp_decay,
    noam_decay,
    piecewise_decay,
    polynomial_decay,
)
from .metric import accuracy, auc, mean_iou  # noqa: F401
from .detection import (  # noqa: F401
    anchor_generator,
    bipartite_match,
    box_clip,
    box_coder,
    density_prior_box,
    detection_map,
    generate_proposals,
    iou_similarity,
    multiclass_nms,
    prior_box,
    roi_align,
    roi_pool,
    rpn_target_assign,
    sigmoid_focal_loss,
    target_assign,
    yolo_box,
    yolov3_loss,
)
from .nn import *  # noqa: F401,F403
from .misc import (  # noqa: F401
    affine_channel,
    affine_grid,
    beam_search,
    beam_search_decode,
    bpr_loss,
    conv3d,
    diag,
    edit_distance,
    expand,
    grid_sampler,
    hinge_loss,
    hsigmoid,
    im2sequence,
    key_padding_bias,
    kldiv_loss,
    log_loss,
    logical_and,
    logical_not,
    logical_or,
    lrn,
    margin_rank_loss,
    maxout,
    multiplex,
    nce,
    pool3d,
    rank_loss,
    reverse,
    row_conv,
    selu,
    spectral_norm,
)
from .sequence import (  # noqa: F401
    crf_decoding,
    linear_chain_crf,
    DynamicRNN,
    StaticRNN,
    dynamic_gru,
    dynamic_lstm,
    attention_bias,
    position_encoding,
    sequence_concat,
    sequence_conv,
    sequence_enumerate,
    sequence_erase,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_mask,
    sequence_pad,
    sequence_pool,
    sequence_reverse,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
    warpctc,
)
from .tensor import (  # noqa: F401
    argmax,
    argmin,
    assign,
    cast,
    concat,
    create_global_var,
    fill_constant,
    linspace,
    ones,
    sums,
    zeros,
    zeros_like,
)


def _dygraph_dispatch(name, graph_fn):
    """Stateless layers work in both modes (reference routes them through
    the imperative Tracer; here: dygraph/functional.py)."""

    @_functools.wraps(graph_fn)
    def wrapper(*a, **k):
        from ..dygraph import base as _db

        if _db.enabled():
            from ..dygraph import functional as _F

            return getattr(_F, name)(*a, **k)
        return graph_fn(*a, **k)

    return wrapper


for _n in (
    "mean", "relu", "softmax", "matmul", "reshape", "transpose", "concat",
    "reduce_sum", "reduce_mean", "square_error_cost", "cross_entropy",
    "softmax_with_cross_entropy", "accuracy", "dropout", "sigmoid", "tanh",
    "sqrt", "square", "exp", "log",
):
    globals()[_n] = _dygraph_dispatch(_n, globals()[_n])
del _n
