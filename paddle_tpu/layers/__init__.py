"""fluid.layers-compatible namespace."""
from .io import data  # noqa: F401
from .metric import accuracy  # noqa: F401
from .nn import *  # noqa: F401,F403
from .tensor import (  # noqa: F401
    argmax,
    argmin,
    assign,
    cast,
    concat,
    create_global_var,
    fill_constant,
    ones,
    sums,
    zeros,
    zeros_like,
)
