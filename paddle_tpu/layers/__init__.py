"""fluid.layers-compatible namespace."""
from .control_flow import (  # noqa: F401
    While,
    array_length,
    array_read,
    array_write,
    cond,
    create_array,
    equal,
    greater_than,
    increment,
    less_than,
)
from .io import data  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    cosine_decay,
    exponential_decay,
    inverse_time_decay,
    linear_lr_warmup,
    natural_exp_decay,
    noam_decay,
    piecewise_decay,
    polynomial_decay,
)
from .metric import accuracy  # noqa: F401
from .nn import *  # noqa: F401,F403
from .tensor import (  # noqa: F401
    argmax,
    argmin,
    assign,
    cast,
    concat,
    create_global_var,
    fill_constant,
    ones,
    sums,
    zeros,
    zeros_like,
)
