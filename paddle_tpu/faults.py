"""Deterministic fault injection: the chaos harness that proves the
resilience layer actually survives what it claims to survive.

Large-scale-training lore says every recovery path you have not tested is
broken; this module makes the four failure classes of errors.py
reproducible on CPU in tier-1 tests.  A `FaultInjector` is driven by a
schedule string (`FLAGS_fault_spec` or the constructor), every entry
fires exactly once, and nothing here depends on wall time or real
hardware — the same spec injects the same faults at the same points on
every run.

Spec grammar (entries separated by ';', whitespace ignored):

    bad_batch@B           raw loader batch B raises DataError when pulled
    nan@S                 the feed of train step S gets a planted NaN, so
                          the real computation produces NaN and the
                          FLAGS_check_nan_inf guard trips at resolution
    device@S[:CODE]       dispatch of train step S raises
                          TransientDeviceError (CODE defaults to
                          UNAVAILABLE; RESOURCE_EXHAUSTED exercises the
                          max_inflight degradation path)
    preempt@S             dispatch of train step S delivers SIGTERM to
                          this process (the real preemption notice, so
                          the loop's deferred-flush handler is what gets
                          tested)

data-layer entries (ISSUE 5) mutate RecordIO files ON DISK via the
`on_files` hook (called by tests/bench with the pipeline's file list
before the loader opens them), so the corruption exercises the real
native scanner + CRC + FLAGS_data_corrupt_budget machinery:

    corrupt_chunk@N       flip a payload byte of global chunk N (counted
                          across the file list) — the CRC catches it and
                          the budget decides skip vs abort
    truncated_file@N      cut the file mid-payload of global chunk N (the
                          torn-write / partial-copy failure mode)

distributed entries (ISSUE 4) target a specific worker RANK; every
worker of a gang parses the same spec and an entry fires only in the
process whose rank matches (`PADDLE_TRAINER_ID`, or the `rank` ctor
arg), so one spec string drives a whole deterministic multi-worker
chaos schedule:

    kill_worker@S:RANK        worker RANK dies with SIGKILL at dispatch
                              of train step S — no cleanup, no tombstone:
                              the hard death peers must detect by
                              heartbeat staleness
    stall_worker@S:RANK:SECS  worker RANK sleeps SECS at dispatch of
                              step S (the straggler that trips the
                              collective watchdog when SECS exceeds its
                              deadline)

ranked entries fire once per GANG, not once per process: a gang restart
replays the failed step, so without cross-incarnation memory the same
kill would fire forever.  When `PADDLE_FAULT_STATE_DIR` names a shared
directory (paddle_tpu.launch exports one per run_gang call), a ranked
entry drops a `fired-...` marker there at its firing point — written
BEFORE the SIGKILL lands — and every later incarnation treats marked
entries as already spent.

silent-corruption entries (ISSUE 14) plant wrong-but-FINITE state — the
class every NaN guard, CRC, and structure check waves through, which
only the integrity sentinel (paddle_tpu/integrity.py) can catch:

    flip_bit@S[:RANK]     at the dispatch boundary of train step S (the
                          feed/snapshot boundary — `on_state`, called by
                          resilient_train_loop with the scope), flip one
                          exponent-region bit of one element of the
                          LARGEST float state var: the value stays finite
                          but wildly implausible, the live cross-rank
                          digests diverge, and the divergence vote must
                          name RANK.  Without :RANK it fires in every
                          process that reaches step S (the
                          single-process form)
    rot_shard@N           flip a payload byte of one shard file of the
                          Nth COMMITTED checkpoint (0-based commit
                          ordinal; `on_commit`, called post-COMMIT) —
                          restore's walk-back must reject the rotted
                          checkpoint by digest and the publish ladder
                          must quarantine it

storage entries (ISSUE 15) fire inside the I/O choke point every
checkpoint/manifest/sidecar/model-store byte routes through
(`io.atomic_write` / `io.open_for_read`; `arm_io()` registers this
injector as the hook, `disarm_io()` removes it —
`resilient_train_loop` arms automatically).  Step-window kinds track
the current train step via `on_dispatch`/`set_step`; op-indexed kinds
count choke-point operations.  Two exemptions keep injection
deterministic: paths under `FLAGS_ckpt_fallback_dir` (the fallback dir
models a different device, so a full primary disk must not also break
it) and heartbeat-transport beats (the beat thread writes on its own
clock — counting it would make op indices timing-dependent, and
failing it would fake the rank's death instead of exercising degraded
mode; real heartbeat-store failures still go loud via
`dist.heartbeat.send_errors`):

    enospc@S[:RANK]       every WRITE during train step S raises
                          OSError(ENOSPC) — the save at step S fails
                          all its retries, the next period's succeeds
                          (the transient-full-disk window).  With :RANK
                          only that worker's writes fail
    ro_fs@S[:RANK]        every WRITE from step S ONWARD raises
                          OSError(EROFS) — the terminal read-only-mount
                          class that must skip retries and go straight
                          to the fallback dir / degraded mode
    eio@N[:PATH_GLOB]     the Nth (0-based) choke-point operation (read
                          or write) whose path fnmatches PATH_GLOB
                          (default *) raises OSError(EIO), exactly once
                          — the one-shot flaky read a retry survives
    slow_io@N:MS          the Nth choke-point operation sleeps MS
                          milliseconds first (storage latency spike),
                          then proceeds

host-tier entries (ISSUE 19) target the supervised parameter server of
an online-learning run (`set_pserver(supervisor)` registers the live
handle; entries stay pending without one):

    kill_pserver@S        SIGKILL the pserver CHILD PROCESS at dispatch
                          of train step S — the supervisor must respawn
                          it (journal recovery, bit-identical) and
                          KVClient's retry loop must ride out the gap
    stall_pserver@S:SECS  SIGSTOP the pserver child for SECS at dispatch
                          of step S: beats stop, FleetHealth declares it
                          dead past the deadline, the supervisor
                          kill+respawns (the wedged-not-dead mode)
    rot_row@N             flip a payload byte of a SelectedRows VALUES
                          shard of the Nth COMMITTED snapshot
                          (`on_commit`, like rot_shard) — the flipped
                          row is finite and silent; the publish ladder's
                          sparse digest rung must quarantine it

    e.g.  FLAGS_fault_spec="bad_batch@2;nan@5;device@7:RESOURCE_EXHAUSTED;preempt@11"
          FLAGS_fault_spec="kill_worker@3:1;stall_worker@6:0:0.2"
          FLAGS_fault_spec="flip_bit@5:1;rot_shard@0"
          FLAGS_fault_spec="enospc@4:1;eio@0:*__manifest__*;slow_io@2:250"

`seed` only feeds the poison-value RNG; firing points are exact indices.
The hooks (`on_batch`, `on_feed`, `on_dispatch`) are called by
`resilient_train_loop`'s feed path and dispatch callback; they are cheap
no-ops once every entry has fired.

Compound schedules (ISSUE 20): `KIND_INFO` publishes per-kind
compatibility metadata (what the index counts, which runtime hooks the
kind needs, whether its firing is ledgered across gang restarts) so the
chaos campaign generator (paddle_tpu/chaos.py) can draw only schedules
every entry of which can actually fire in the chosen scenario;
`validate_schedule` rejects specs with exact-duplicate entries,
capability mismatches, or unreachable pairings (an enospc window
shadowed by an earlier ro_fs).  `sweep_stale_ledgers` reclaims
`PADDLE_FAULT_STATE_DIR` markers left by dead gangs — call it only at
run START (run_gang / campaign entry), never between incarnations of a
live gang: a SIGKILLed child's marker has a dead PID by design and must
keep suppressing its entry until the whole run is over.
"""
from __future__ import annotations

__all__ = ["Fault", "FaultInjector", "parse_fault_spec", "KIND_INFO",
           "validate_schedule", "sweep_stale_ledgers"]

import errno as _errno
import fnmatch
import os
import random
import signal
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from .errors import DataError, TransientDeviceError
from .monitor import MONITOR as _MON

_KINDS = ("bad_batch", "nan", "device", "preempt",
          "kill_worker", "stall_worker",
          "corrupt_chunk", "truncated_file",
          "flip_bit", "rot_shard",
          "enospc", "eio", "slow_io", "ro_fs",
          "kill_pserver", "stall_pserver", "rot_row")
# entries that only fire in the worker whose rank matches their arg
# (flip_bit is rank-gated too, but its rank is OPTIONAL — handled via
# target_rank, which answers None for the rankless single-process form)
_RANKED_KINDS = ("kill_worker", "stall_worker")
# storage faults (ISSUE 15): fire inside the io.py choke point via the
# on_io hook.  enospc/ro_fs are step-WINDOW kinds (active while the
# tracked train step is at/past their index — a save's whole retry
# sequence at step S fails, the next period's save succeeds); eio/
# slow_io are op-INDEXED one-shots (the Nth matching choke-point
# operation).  enospc/ro_fs take an optional rank like flip_bit
_STORAGE_KINDS = ("enospc", "eio", "slow_io", "ro_fs")
_STORAGE_ERRNO = {"enospc": _errno.ENOSPC, "eio": _errno.EIO,
                  "ro_fs": _errno.EROFS}
# on-disk data faults (ISSUE 5): mutate RecordIO files handed to
# `on_files` — corrupt_chunk@N flips a payload byte of the Nth chunk
# (CRC catches it), truncated_file@N cuts the file mid-payload of the
# Nth chunk.  Both exercise the recordio corrupt-budget path
_FILE_KINDS = ("corrupt_chunk", "truncated_file")
# entries whose firing must survive a gang restart: a restarted worker
# replays the failed step (and re-opens its files), so without the
# PADDLE_FAULT_STATE_DIR ledger the same fault would fire forever.
# flip_bit replays too (the restart restores PRE-flip state and replays
# step S); rot_shard's marker doubles as the cross-rank mutex — every
# rank observes the commit, exactly one may mutate the shard.  Storage
# entries replay for the same reason: a restarted gang replays the step
# whose failed save triggered the restart, and a fault that re-fires
# forever would starve the run of checkpoints
_LEDGER_KINDS = _RANKED_KINDS + _FILE_KINDS \
    + ("flip_bit", "rot_shard", "rot_row",
       "kill_pserver", "stall_pserver") + _STORAGE_KINDS
# host-tier chaos (ISSUE 19): these need a live handle on the pserver's
# supervisor (`set_pserver`) — kill_pserver@S SIGKILLs the pserver child
# at dispatch of step S (the supervisor must respawn it and KVClient's
# retry loop must ride the gap out); stall_pserver@S:SECS SIGSTOPs it
# for SECS (beats stop, FleetHealth declares it dead, the supervisor
# kill+respawns); rot_row@N flips a byte inside a SelectedRows VALUES
# shard of the Nth committed snapshot (on_commit, like rot_shard) — the
# publish ladder's sparse rung must quarantine it
_PSERVER_KINDS = ("kill_pserver", "stall_pserver")

# Per-kind compatibility metadata (ISSUE 20).  The chaos campaign
# generator draws schedules from this table; scenarios declare the
# capabilities they provide and only kinds whose `needs` are covered are
# eligible.  Fields:
#   grammar  — the spec-grammar line, verbatim from the docstring table
#              (the self-consistency test asserts it appears there)
#   scope    — what the entry's index counts: "batch" (raw loader
#              batch), "step" (train step), "chunk" (global RecordIO
#              chunk), "commit" (committed checkpoint ordinal), "op"
#              (choke-point I/O operation)
#   needs    — runtime hooks/capabilities the kind requires to fire:
#              "loader" (on_batch), "feed" (on_feed), "dispatch"
#              (on_dispatch), "scope" (on_state with a live scope),
#              "commit" (on_commit), "files" (on_files with RecordIO
#              paths), "io" (arm_io around real io.py traffic), "gang"
#              (a multi-worker gang whose supervisor restarts the
#              victim), "pserver" (a registered PServerSupervisor)
#   ledgered — firing survives gang restarts via the
#              PADDLE_FAULT_STATE_DIR marker ledger
#   example  — one valid spec entry (parse_fault_spec must accept it)
KIND_INFO = {
    "bad_batch": dict(grammar="bad_batch@B", scope="batch",
                      needs=("loader",), example="bad_batch@2"),
    "nan": dict(grammar="nan@S", scope="step",
                needs=("feed",), example="nan@3"),
    "device": dict(grammar="device@S[:CODE]", scope="step",
                   needs=("dispatch",), example="device@4:UNAVAILABLE"),
    "preempt": dict(grammar="preempt@S", scope="step",
                    needs=("dispatch",), example="preempt@5"),
    "kill_worker": dict(grammar="kill_worker@S:RANK", scope="step",
                        needs=("dispatch", "gang"),
                        example="kill_worker@3:1"),
    "stall_worker": dict(grammar="stall_worker@S:RANK:SECS", scope="step",
                         needs=("dispatch", "gang"),
                         example="stall_worker@6:0:0.2"),
    "corrupt_chunk": dict(grammar="corrupt_chunk@N", scope="chunk",
                          needs=("files",), example="corrupt_chunk@1"),
    "truncated_file": dict(grammar="truncated_file@N", scope="chunk",
                           needs=("files",), example="truncated_file@1"),
    "flip_bit": dict(grammar="flip_bit@S[:RANK]", scope="step",
                     needs=("scope",), example="flip_bit@5:1"),
    "rot_shard": dict(grammar="rot_shard@N", scope="commit",
                      needs=("commit",), example="rot_shard@0"),
    "enospc": dict(grammar="enospc@S[:RANK]", scope="step",
                   needs=("io",), example="enospc@4"),
    "ro_fs": dict(grammar="ro_fs@S[:RANK]", scope="step",
                  needs=("io",), example="ro_fs@6"),
    "eio": dict(grammar="eio@N[:PATH_GLOB]", scope="op",
                needs=("io",), example="eio@0"),
    "slow_io": dict(grammar="slow_io@N:MS", scope="op",
                    needs=("io",), example="slow_io@2:250"),
    "kill_pserver": dict(grammar="kill_pserver@S", scope="step",
                         needs=("dispatch", "pserver"),
                         example="kill_pserver@3"),
    "stall_pserver": dict(grammar="stall_pserver@S:SECS", scope="step",
                          needs=("dispatch", "pserver"),
                          example="stall_pserver@3:0.5"),
    "rot_row": dict(grammar="rot_row@N", scope="commit",
                    needs=("commit", "pserver"), example="rot_row@0"),
}
for _k, _info in KIND_INFO.items():
    _info["ledgered"] = _k in _LEDGER_KINDS


@dataclass
class Fault:
    kind: str
    at: int
    arg: Optional[str] = None
    fired: bool = False
    # op-indexed storage entries count their matching choke-point
    # operations here; `exhausted` marks an entry spent by a previous
    # gang incarnation's ledger marker (inactive forever, unlike a
    # step-window entry that stays active while its step lasts)
    seen: int = 0
    exhausted: bool = False

    def __str__(self):
        s = f"{self.kind}@{self.at}"
        return f"{s}:{self.arg}" if self.arg else s

    @property
    def target_rank(self) -> Optional[int]:
        """Worker rank a ranked entry targets (None for per-process kinds
        and for the rankless flip_bit@S / enospc@S / ro_fs@S forms)."""
        if self.kind in ("flip_bit", "enospc", "ro_fs"):
            return int(self.arg) if self.arg else None
        if self.kind not in _RANKED_KINDS or not self.arg:
            return None
        return int(self.arg.split(":", 1)[0])

    @property
    def stall_s(self) -> float:
        assert self.kind == "stall_worker"
        return float(self.arg.split(":", 1)[1])

    @property
    def slow_ms(self) -> float:
        assert self.kind == "slow_io"
        return float(self.arg)

    @property
    def pserver_stall_s(self) -> float:
        assert self.kind == "stall_pserver"
        return float(self.arg)


def parse_fault_spec(spec: str) -> List[Fault]:
    faults = []
    for raw in (spec or "").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        kind, sep, rest = entry.partition("@")
        kind = kind.strip()
        if not sep or kind not in _KINDS:
            raise ValueError(
                f"fault spec entry {entry!r}: want kind@N[:arg] with kind in "
                f"{_KINDS} (full spec {spec!r})")
        at_s, _, arg = rest.partition(":")
        try:
            at = int(at_s)
        except ValueError:
            raise ValueError(f"fault spec entry {entry!r}: {at_s!r} is not "
                             f"an integer index")
        arg = arg.strip() or None
        f = Fault(kind, at, arg)
        if kind == "kill_worker":
            if arg is None or not arg.isdigit():
                raise ValueError(f"fault spec entry {entry!r}: want "
                                 f"kill_worker@STEP:RANK")
        elif kind == "stall_worker":
            parts = (arg or "").split(":")
            ok = len(parts) == 2 and parts[0].isdigit()
            if ok:
                try:
                    float(parts[1])
                except ValueError:
                    ok = False
            if not ok:
                raise ValueError(f"fault spec entry {entry!r}: want "
                                 f"stall_worker@STEP:RANK:SECONDS")
        elif kind == "flip_bit":
            if arg is not None and not arg.isdigit():
                raise ValueError(f"fault spec entry {entry!r}: want "
                                 f"flip_bit@STEP or flip_bit@STEP:RANK")
        elif kind == "rot_shard":
            if arg is not None:
                raise ValueError(f"fault spec entry {entry!r}: want "
                                 f"rot_shard@COMMIT_INDEX (no extra arg)")
        elif kind in ("enospc", "ro_fs"):
            if arg is not None and not arg.isdigit():
                raise ValueError(f"fault spec entry {entry!r}: want "
                                 f"{kind}@STEP or {kind}@STEP:RANK")
        elif kind == "slow_io":
            try:
                ok = arg is not None and float(arg) >= 0
            except ValueError:
                ok = False
            if not ok:
                raise ValueError(f"fault spec entry {entry!r}: want "
                                 f"slow_io@OP_INDEX:MILLISECONDS")
        elif kind in ("kill_pserver", "rot_row"):
            if arg is not None:
                raise ValueError(f"fault spec entry {entry!r}: want "
                                 f"{kind}@{'STEP' if kind == 'kill_pserver' else 'COMMIT_INDEX'} (no extra arg)")
        elif kind == "stall_pserver":
            try:
                ok = arg is not None and float(arg) > 0
            except ValueError:
                ok = False
            if not ok:
                raise ValueError(f"fault spec entry {entry!r}: want "
                                 f"stall_pserver@STEP:SECONDS")
        faults.append(f)
    return faults


def validate_schedule(spec, capabilities=None) -> List[Fault]:
    """Compound-schedule validation (ISSUE 20): parse `spec` (a
    FLAGS_fault_spec string or an already-parsed fault list) and reject
    schedules that cannot behave deterministically as a compound:

      * exact-duplicate entries — the second copy could never fire (the
        ledger marker or the one-shot latch suppresses it), so the spec
        would silently mean less than it says;
      * entries whose `needs` (KIND_INFO) exceed `capabilities` — when a
        capability set is given, every entry must be able to fire in the
        scenario providing it;
      * an enospc window at/after a ro_fs window targeting the same rank
        — ro_fs fails every later write first, so the enospc entry is
        unreachable dead weight.

    Returns the parsed fault list on success; raises ValueError naming
    the offending entries otherwise."""
    faults = parse_fault_spec(spec) if isinstance(spec, str) else list(spec)
    seen: set = set()
    for f in faults:
        key = (f.kind, f.at, f.arg)
        if key in seen:
            raise ValueError(
                f"fault schedule {spec!r}: duplicate entry {f} — the "
                f"second copy can never fire (one-shot latch / ledger "
                f"marker suppresses it)")
        seen.add(key)
    if capabilities is not None:
        caps = frozenset(capabilities)
        for f in faults:
            missing = [n for n in KIND_INFO[f.kind]["needs"]
                       if n not in caps]
            if missing:
                raise ValueError(
                    f"fault schedule {spec!r}: entry {f} needs "
                    f"{missing} but the scenario only provides "
                    f"{sorted(caps)}")
    ro = [f for f in faults if f.kind == "ro_fs"]
    for f in faults:
        if f.kind != "enospc":
            continue
        for r in ro:
            same_rank = (r.target_rank is None
                         or f.target_rank is None
                         or r.target_rank == f.target_rank)
            if same_rank and f.at >= r.at:
                raise ValueError(
                    f"fault schedule {spec!r}: {f} is unreachable — "
                    f"{r} already fails every write from step {r.at} "
                    f"onward")
    return faults


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists but not ours — definitely alive
    return True


def sweep_stale_ledgers(state_dir: Optional[str] = None,
                        scan_tmp: bool = True,
                        min_age_s: float = 3600.0) -> Dict[str, int]:
    """Reclaim fault-ledger state left by DEAD gangs (ISSUE 20): every
    `fired-*` marker records the writing PID, so a marker whose PID is
    gone belongs to a finished (or aborted) run and would wrongly
    suppress the same fault in the next run that reuses the directory.
    Additionally sweeps leaked `pt-fault-state-*` tempdirs (run_gang
    mints one per run with no checkpoint_root; an aborted chaos run
    leaks it).

    Call ONLY at run start (run_gang / campaign entry), never between
    incarnations of a live gang: a SIGKILLed child's marker has a dead
    PID by design and must keep suppressing its entry until the whole
    run is over.

    Empty tempdirs are only removed past `min_age_s` (a concurrent
    run_gang may have just minted one it has not written to yet).
    Returns {"markers": removed_marker_count, "dirs": removed_dirs}."""
    removed = {"markers": 0, "dirs": 0}

    def _sweep_markers(d: str) -> int:
        n = 0
        try:
            names = os.listdir(d)
        except OSError:
            return 0
        for name in names:
            if not name.startswith("fired-"):
                continue
            path = os.path.join(d, name)
            try:
                with open(path) as fh:
                    pid = int(fh.read().strip() or "0")
            except (OSError, ValueError):
                pid = 0  # unreadable/unparseable: treat as dead
            if pid <= 0 or not _pid_alive(pid):
                try:
                    os.unlink(path)
                    n += 1
                except OSError:
                    pass
        return n

    if state_dir is None:
        state_dir = os.environ.get("PADDLE_FAULT_STATE_DIR")
    if state_dir and os.path.isdir(state_dir):
        removed["markers"] += _sweep_markers(state_dir)
    if scan_tmp:
        import shutil
        import tempfile

        tmp = tempfile.gettempdir()
        try:
            entries = os.listdir(tmp)
        except OSError:
            entries = []
        for name in entries:
            if not name.startswith("pt-fault-state-"):
                continue
            d = os.path.join(tmp, name)
            if not os.path.isdir(d) \
                    or os.path.abspath(d) == os.path.abspath(state_dir or ""):
                continue
            try:
                markers = [m for m in os.listdir(d)
                           if m.startswith("fired-")]
            except OSError:
                continue
            if not markers:
                # just-minted dir of a concurrent gang?  only reclaim
                # once it is old enough that no live run still owns it
                try:
                    age = time.time() - os.path.getmtime(d)
                except OSError:
                    continue
                if age < min_age_s:
                    continue
                removed["dirs"] += 1
                shutil.rmtree(d, ignore_errors=True)
                continue
            live = False
            for m in markers:
                try:
                    with open(os.path.join(d, m)) as fh:
                        pid = int(fh.read().strip() or "0")
                except (OSError, ValueError):
                    pid = 0
                if pid > 0 and _pid_alive(pid):
                    live = True
                    break
            if not live:
                removed["dirs"] += 1
                shutil.rmtree(d, ignore_errors=True)
    return removed


def _mutate_chunk(paths, chunk_at: int, truncate: bool) -> bool:
    """Apply one on-disk data fault: locate global chunk `chunk_at` across
    the RecordIO `paths` (frames counted in list order) and either flip a
    payload byte (CRC mismatch) or truncate the file mid-payload.  Returns
    False when the chunk does not exist (entry stays pending — same
    contract as a step index never reached)."""
    import struct

    seen = 0
    for path in paths:
        with open(path, "rb") as fh:
            data = bytearray(fh.read())
        off = 0
        while off + 20 <= len(data):
            magic, nrecs = struct.unpack_from("<II", data, off)
            (plen,) = struct.unpack_from("<Q", data, off + 8)
            if magic != 0x01020304 or off + 20 + plen > len(data):
                break  # already-broken tail; stop framing this file
            if seen == chunk_at:
                if plen == 0:
                    return False  # nothing to corrupt in an empty chunk
                if truncate:
                    # keep the header + half the payload: the scanner sees
                    # a valid header whose payload read comes up short
                    data = data[:off + 20 + max(1, int(plen) // 2)]
                else:
                    data[off + 20 + int(plen) // 2] ^= 0xFF
                with open(path, "wb") as fh:
                    fh.write(bytes(data))
                return True
            seen += 1
            off += 20 + int(plen)
    return False


class FaultInjector:
    """Seeded, schedule-driven fault source.  One instance = one schedule;
    construct fresh (or `reset()`) per run."""

    def __init__(self, spec: str = "", seed: int = 0,
                 rank: Optional[int] = None):
        self.spec = spec
        self.seed = seed
        self.faults = parse_fault_spec(spec)
        self._rng = random.Random(seed)
        # ranked entries (kill_worker/stall_worker) fire only in the worker
        # whose rank matches; default from the PADDLE_* trainer contract so
        # one FLAGS_fault_spec string drives a whole gang deterministically
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        # once-per-gang ledger for ranked entries (survives gang restarts)
        self.state_dir = os.environ.get("PADDLE_FAULT_STATE_DIR")
        # rot_shard@N / rot_row@N count COMMITTED checkpoints/snapshots
        # this injector observed
        self._commits = 0
        # kill_pserver/stall_pserver need a live supervisor handle
        # (set_pserver); entries stay pending until one is registered
        self._pserver = None
        # storage faults: the train step the loop is currently inside
        # (on_dispatch/set_step maintain it; -1 = no step dispatched yet,
        # so step-window entries stay dormant outside a training loop
        # until a test pins the step explicitly) and the io.py hook state
        self._step = -1
        self._io_prev_hook = None
        self._io_armed = False
        # serializes Fault.seen/fired mutation: the hook can fire from
        # more than one thread (training saves, a server's publish) and
        # an unsynchronized read-modify-write could double-fire or skip
        # a one-shot op-indexed entry.  Claim-only critical section —
        # ledger I/O, prints, sleeps, and the raise all happen after
        # release (blocking work never runs under a framework lock)
        from .core import locks as _locks

        self._io_lock = _locks.named_lock("faults.io", rank=48)
        self._storage = [f for f in self.faults if f.kind in _STORAGE_KINDS]

    @staticmethod
    def from_flags() -> Optional["FaultInjector"]:
        """Build the injector `FLAGS_fault_spec` asks for (None when the
        flag is empty — the production default)."""
        from .flags import flag

        spec = flag("FLAGS_fault_spec")
        return FaultInjector(spec) if spec else None

    def reset(self):
        for f in self.faults:
            f.fired = False
            f.seen = 0
            f.exhausted = False
        self._rng = random.Random(self.seed)
        self._step = -1
        return self

    def pending(self) -> List[Fault]:
        return [f for f in self.faults if not f.fired]

    def fired(self) -> List[Fault]:
        return [f for f in self.faults if f.fired]

    def summary(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.faults:
            if f.fired:
                out[f.kind] = out.get(f.kind, 0) + 1
        return out

    # -- hooks -------------------------------------------------------------
    def _ranked_marker(self, f: Fault) -> Optional[str]:
        if self.state_dir is None or f.kind not in _LEDGER_KINDS:
            return None
        # eio globs may carry path separators; the marker is a flat file
        arg = str(f.arg).replace(os.sep, "%2F")
        return os.path.join(self.state_dir, f"fired-{f.kind}@{f.at}-{arg}")

    def _take(self, kind: str, at: int) -> Optional[Fault]:
        for f in self.faults:
            if f.kind == kind and f.at == at and not f.fired:
                tr = f.target_rank
                if tr is not None and tr != self.rank:
                    continue  # another worker's fault: stays pending here
                marker = self._ranked_marker(f)
                if marker is not None:
                    if os.path.exists(marker):
                        # spent in an earlier gang incarnation: the restart
                        # replays this step, the fault must not replay too
                        f.fired = True
                        continue
                    os.makedirs(self.state_dir, exist_ok=True)
                    with open(marker, "w") as fh:
                        fh.write(str(os.getpid()))
                        fh.flush()
                        os.fsync(fh.fileno())  # must hit disk before SIGKILL
                f.fired = True
                _MON.counter(f"faults.{kind}").inc()
                return f
        return None

    def on_files(self, paths):
        """Called with the RecordIO file list a data pipeline is about to
        open (tests/bench call it explicitly before building the loader);
        applies any pending corrupt_chunk@N / truncated_file@N entries by
        mutating the files ON DISK — the corruption then flows through the
        real native scanner + CRC + budget machinery, not a mock.  Chunk
        index N counts frames across the concatenated file list.  Fires
        once (per gang, when the launcher's fault ledger is armed).
        Returns `paths` for chaining."""
        for kind in _FILE_KINDS:
            for f in list(self.faults):
                if f.kind != kind or f.fired:
                    continue
                marker = self._ranked_marker(f)
                if marker is not None and os.path.exists(marker):
                    f.fired = True  # spent in an earlier gang incarnation
                    continue
                if _mutate_chunk(paths, f.at, truncate=(kind == "truncated_file")):
                    f.fired = True
                    if marker is not None:
                        os.makedirs(self.state_dir, exist_ok=True)
                        with open(marker, "w") as fh:
                            fh.write(str(os.getpid()))
                    _MON.counter(f"faults.{kind}").inc()
        return paths

    def on_batch(self, batch_index: int, feed):
        """Called with every raw batch pulled from the loader; raises
        DataError for a scheduled bad batch (simulating a record the
        parser rejects)."""
        if self._take("bad_batch", batch_index) is not None:
            raise DataError(f"injected bad batch {batch_index}",
                            batch_index=batch_index, phase="loader")
        return feed

    def on_feed(self, step: int, feed: dict) -> dict:
        """Called with the feed about to become train step `step`; plants
        a NaN in the first floating-point array so the NaN reaches the
        loss through the real computation (not a mocked check)."""
        if self._take("nan", step) is None:
            return feed
        feed = dict(feed)
        for name in sorted(feed):
            a = np.asarray(feed[name])
            if np.issubdtype(a.dtype, np.floating) and a.size:
                a = a.copy()
                a.flat[self._rng.randrange(a.size)] = np.nan
                feed[name] = a
                break
        else:
            raise ValueError(f"nan@{step}: feed has no floating-point array "
                             f"to poison (names: {sorted(feed)})")
        return feed

    def on_state(self, step: int, scope):
        """Called at the dispatch boundary of train step `step` with the
        live scope (resilient_train_loop's feed/snapshot boundary — the
        same consistent cut the state snapshots and integrity digests
        use); applies a scheduled flip_bit by XOR-ing one exponent-region
        bit of one seeded element of the LARGEST float state var.  The
        result is deliberately finite — the point is a value every
        NaN/Inf guard waves through and only a content digest can see."""
        if self._take("flip_bit", step) is None:
            return
        # deterministic victim: the LARGEST float var (big tensors are
        # where real SDC lands, and a zero-initialized bias would make a
        # fault too quiet to attribute), name-ordered tiebreak
        floats = []
        for name in sorted(scope.local_var_names()):
            v = scope.find_var(name)
            try:
                a = np.asarray(v)
            except Exception:
                continue
            if a.dtype.kind == "f" and a.size \
                    and a.dtype.itemsize in (2, 4, 8):
                floats.append((-a.size, name, a))
        floats.sort(key=lambda t: (t[0], t[1]))
        for _neg, name, a in floats:
            a = a.copy()
            flat = a.reshape(-1)
            idx = self._rng.randrange(flat.size)
            bits = flat.view({2: np.uint16, 4: np.uint32,
                              8: np.uint64}[a.dtype.itemsize])
            width = a.dtype.itemsize * 8
            # top exponent bit first (0.02 -> ~1e36: finite, loud for the
            # plausibility tiebreak); walk down if a flip would produce
            # NaN/Inf — the fault must stay FINITE or the NaN guard would
            # catch it and the test would prove nothing
            for b in range(width - 2, width - 8, -1):
                old = bits[idx]
                bits[idx] = old ^ type(bits[idx])(1 << b)
                if np.isfinite(flat[idx]):
                    break
                bits[idx] = old
            else:
                flat[idx] = flat.dtype.type(
                    {16: 6e4, 32: 3e38, 64: 1e300}[width])
            print(f"faults: flip_bit@{step} firing on {name!r}[{idx}] "
                  f"(rank {self.rank})", file=sys.stderr, flush=True)
            scope.set_var(name, a)
            return
        raise ValueError(f"flip_bit@{step}: scope has no float state var "
                         f"to corrupt")

    def on_commit(self, ckpt_dir: Optional[str]):
        """Called with each checkpoint directory the moment its COMMIT
        lands (resilient_train_loop's flush path; tests/bench call it
        directly); applies a pending rot_shard@N when this is the Nth
        commit this injector (or, with the fault ledger armed, this
        GANG) observed.  The ledger marker is created with O_EXCL before
        mutating, so exactly one rank of a coordinated save rots the
        shard and a restarted gang never re-rots.  Returns `ckpt_dir`
        for chaining."""
        idx = self._commits
        self._commits += 1
        for f in self.faults:
            if f.kind not in ("rot_shard", "rot_row") or f.at != idx \
                    or f.fired:
                continue
            marker = self._ranked_marker(f)
            if marker is not None and os.path.exists(marker):
                f.fired = True  # spent in an earlier gang incarnation
                continue
            if ckpt_dir is None or not os.path.isdir(ckpt_dir):
                # a non-committing rank of a coordinated save: the dir
                # may not have been renamed into place yet.  The ordinal
                # was counted (every rank sees the same save sequence);
                # the committing rank performs the mutation.
                continue
            if marker is not None:
                os.makedirs(self.state_dir, exist_ok=True)
                try:
                    with open(marker, "x") as fh:
                        fh.write(str(os.getpid()))
                except FileExistsError:
                    f.fired = True  # another rank won the mutation
                    continue
            if self._rot_one_shard(ckpt_dir, f):
                f.fired = True
                _MON.counter(f"faults.{f.kind}").inc()
        return ckpt_dir

    def _rot_one_shard(self, ckpt_dir: str, f: Fault) -> bool:
        """Flip one payload byte of the first shard file (sorted order).
        rot_shard takes any .npy; rot_row targets a SelectedRows VALUES
        shard (`*.vals.*.npy` — the embedding rows of the sparse tier),
        the silent flipped-row the publish ladder's sparse rung must
        catch by digest."""
        shards = sorted(n for n in os.listdir(ckpt_dir)
                        if n.endswith(".npy")
                        and (f.kind != "rot_row" or ".vals." in n))
        if not shards:
            return False
        path = os.path.join(ckpt_dir, shards[0])
        size = os.path.getsize(path)
        if size == 0:
            return False
        with open(path, "r+b") as fh:
            fh.seek(size // 2)
            b = fh.read(1)
            fh.seek(size // 2)
            fh.write(bytes([b[0] ^ 0xFF]))
        print(f"faults: rot_shard@{f.at} firing on {path} "
              f"(byte {size // 2} flipped post-COMMIT)",
              file=sys.stderr, flush=True)
        return True

    # -- storage faults (ISSUE 15) -----------------------------------------
    def arm_io(self) -> "FaultInjector":
        """Register this injector as the io.py choke-point fault hook so
        enospc/eio/slow_io/ro_fs entries can fire on real checkpoint/
        manifest/model-store I/O.  Idempotent; `disarm_io` restores the
        previous hook.  `resilient_train_loop` arms/disarms automatically
        around its run."""
        if not self._io_armed:
            from . import io as _io

            self._io_prev_hook = _io.set_io_fault_hook(self.on_io)
            self._io_armed = True
        return self

    def disarm_io(self):
        if self._io_armed:
            from . import io as _io

            _io.set_io_fault_hook(self._io_prev_hook)
            self._io_prev_hook = None
            self._io_armed = False

    def set_step(self, step: int):
        """Pin the train step the step-window storage entries compare
        against (`on_dispatch` calls this; tests driving CheckpointManager
        directly call it by hand)."""
        self._step = int(step)

    def _spend_ledgered(self, f: Fault) -> bool:
        """True when a previous gang incarnation already fired `f` (ledger
        marker present) — the entry goes inactive; otherwise the marker is
        written (plain open: the ledger dir is not storage under test) and
        the caller fires."""
        marker = self._ranked_marker(f)
        if marker is None:
            return False
        if os.path.exists(marker):
            f.fired = True
            f.exhausted = True
            return True
        os.makedirs(self.state_dir, exist_ok=True)
        with open(marker, "w") as fh:
            fh.write(str(os.getpid()))
        return False

    def on_io(self, op: str, path: str):
        """The io.py choke-point hook: applies any armed storage fault to
        this operation.  `op` is "read" or "write".  Raises plain OSError
        with the real errno — the io layer stamps phase="storage" and
        errors.classify maps it onto StorageError, exactly the path a real
        disk failure takes.  Paths under FLAGS_ckpt_fallback_dir are
        exempt (the fallback models a different device)."""
        live = [f for f in self._storage if not f.exhausted]
        if not live:
            return
        from . import io as _io
        from .flags import flag as _flag

        exempt = list(_io.fault_exempt_prefixes())
        fb = _flag("FLAGS_ckpt_fallback_dir")
        if fb:
            exempt.append(os.path.abspath(fb))
        if exempt:
            ap = os.path.abspath(path)
            for pfx in exempt:
                if ap == pfx or ap.startswith(pfx + os.sep):
                    return
        # CLAIM under the lock (pure bookkeeping: op-index counters and
        # the first-fire latch, so concurrent threads can never double-
        # fire or skip a one-shot), then FIRE outside it — the ledger's
        # file I/O, the stderr print, the slow_io sleep, and the raise
        # are all blocking work that must not serialize other threads'
        # I/O through a held framework lock.
        hits = []  # (fault, first_fire)
        with self._io_lock:
            for f in live:
                if f.kind in ("slow_io", "eio"):
                    if f.kind == "eio" and \
                            not fnmatch.fnmatch(path, f.arg or "*"):
                        continue
                    idx, f.seen = f.seen, f.seen + 1
                    if idx == f.at:
                        f.fired = True
                        hits.append((f, True))  # op index unique: one claimant
                    continue
                # step-window kinds: enospc (step == at), ro_fs (step >= at)
                if op != "write":
                    continue
                tr = f.target_rank
                if tr is not None and tr != self.rank:
                    continue
                if self._step < 0:
                    continue
                active = (self._step == f.at if f.kind == "enospc"
                          else self._step >= f.at)
                if active:
                    first, f.fired = not f.fired, True
                    hits.append((f, first))
        sleep_ms = 0.0
        err = None
        for f, first in hits:
            if first and self._spend_ledgered(f):
                continue  # spent by an earlier gang incarnation
            if f.kind == "slow_io":
                _MON.counter("faults.slow_io").inc()
                print(f"faults: slow_io@{f.at} firing on {path} "
                      f"({f.slow_ms}ms)", file=sys.stderr, flush=True)
                sleep_ms += f.slow_ms
                continue
            if first:
                _MON.counter(f"faults.{f.kind}").inc()
                at = (f"op {f.at}" if f.kind == "eio"
                      else f"step {self._step}")
                print(f"faults: {f} firing at {at} on {path} "
                      f"(rank {self.rank})", file=sys.stderr, flush=True)
            err = OSError(_STORAGE_ERRNO[f.kind],
                          f"injected {f.kind.upper().replace('_', '-')} "
                          f"(fault {f})", path)
        if sleep_ms:
            time.sleep(sleep_ms / 1e3)
        if err is not None:
            raise err

    def on_dispatch(self, step: int):
        """Called just before train step `step` is dispatched; raises the
        scheduled transient device error, delivers a real SIGTERM (the
        preemption notice), hard-kills this worker (SIGKILL — no cleanup,
        no tombstone: peers must detect the death by heartbeat staleness),
        or stalls it to fake a straggler.  Also advances the storage
        faults' step tracker (enospc/ro_fs windows follow the train
        step)."""
        self.set_step(step)
        f = self._take("device", step)
        if f is not None:
            code = f.arg or "UNAVAILABLE"
            raise TransientDeviceError(
                f"injected device failure ({code}) at dispatch {step}",
                code=code, step=step, phase="device")
        if self._take("preempt", step) is not None:
            os.kill(os.getpid(), signal.SIGTERM)
        f = self._take("kill_worker", step)
        if f is not None:
            print(f"faults: kill_worker@{step}:{self.rank} firing (SIGKILL)",
                  file=sys.stderr, flush=True)
            # the victim's own last words: dump the flight recorder BEFORE
            # the SIGKILL (fsynced, so the black box survives the kill) —
            # this is the only record a hard-killed rank ever leaves
            _MON.dump_blackbox(f"kill_worker@{step}:{self.rank}")
            os.kill(os.getpid(), signal.SIGKILL)
        f = self._take("stall_worker", step)
        if f is not None:
            _MON.counter("faults.stall_seconds").inc(int(f.stall_s))
            time.sleep(f.stall_s)
        # host-tier chaos (ISSUE 19): only claimable once a supervisor is
        # registered — without one the entries stay pending, same contract
        # as a step index never reached
        if self._pserver is not None:
            f = self._take("kill_pserver", step)
            if f is not None:
                print(f"faults: kill_pserver@{step} firing (SIGKILL on the "
                      f"pserver child)", file=sys.stderr, flush=True)
                self._pserver.kill()
            f = self._take("stall_pserver", step)
            if f is not None:
                print(f"faults: stall_pserver@{step} firing (SIGSTOP "
                      f"{f.pserver_stall_s}s)", file=sys.stderr, flush=True)
                self._pserver.stall(f.pserver_stall_s)

    def set_pserver(self, supervisor) -> "FaultInjector":
        """Register the PServerSupervisor the kill_pserver/stall_pserver
        entries act on (anything with .kill() / .stall(seconds) works).
        Returns self for chaining."""
        self._pserver = supervisor
        return self
