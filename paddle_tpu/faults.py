"""Deterministic fault injection: the chaos harness that proves the
resilience layer actually survives what it claims to survive.

Large-scale-training lore says every recovery path you have not tested is
broken; this module makes the four failure classes of errors.py
reproducible on CPU in tier-1 tests.  A `FaultInjector` is driven by a
schedule string (`FLAGS_fault_spec` or the constructor), every entry
fires exactly once, and nothing here depends on wall time or real
hardware — the same spec injects the same faults at the same points on
every run.

Spec grammar (entries separated by ';', whitespace ignored):

    bad_batch@B           raw loader batch B raises DataError when pulled
    nan@S                 the feed of train step S gets a planted NaN, so
                          the real computation produces NaN and the
                          FLAGS_check_nan_inf guard trips at resolution
    device@S[:CODE]       dispatch of train step S raises
                          TransientDeviceError (CODE defaults to
                          UNAVAILABLE; RESOURCE_EXHAUSTED exercises the
                          max_inflight degradation path)
    preempt@S             dispatch of train step S delivers SIGTERM to
                          this process (the real preemption notice, so
                          the loop's deferred-flush handler is what gets
                          tested)

    e.g.  FLAGS_fault_spec="bad_batch@2;nan@5;device@7:RESOURCE_EXHAUSTED;preempt@11"

`seed` only feeds the poison-value RNG; firing points are exact indices.
The hooks (`on_batch`, `on_feed`, `on_dispatch`) are called by
`resilient_train_loop`'s feed path and dispatch callback; they are cheap
no-ops once every entry has fired.
"""
from __future__ import annotations

__all__ = ["Fault", "FaultInjector", "parse_fault_spec"]

import os
import random
import signal
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from .errors import DataError, TransientDeviceError
from .monitor import MONITOR as _MON

_KINDS = ("bad_batch", "nan", "device", "preempt")


@dataclass
class Fault:
    kind: str
    at: int
    arg: Optional[str] = None
    fired: bool = False

    def __str__(self):
        s = f"{self.kind}@{self.at}"
        return f"{s}:{self.arg}" if self.arg else s


def parse_fault_spec(spec: str) -> List[Fault]:
    faults = []
    for raw in (spec or "").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        kind, sep, rest = entry.partition("@")
        kind = kind.strip()
        if not sep or kind not in _KINDS:
            raise ValueError(
                f"fault spec entry {entry!r}: want kind@N[:arg] with kind in "
                f"{_KINDS} (full spec {spec!r})")
        at_s, _, arg = rest.partition(":")
        try:
            at = int(at_s)
        except ValueError:
            raise ValueError(f"fault spec entry {entry!r}: {at_s!r} is not "
                             f"an integer index")
        faults.append(Fault(kind, at, arg.strip() or None))
    return faults


class FaultInjector:
    """Seeded, schedule-driven fault source.  One instance = one schedule;
    construct fresh (or `reset()`) per run."""

    def __init__(self, spec: str = "", seed: int = 0):
        self.spec = spec
        self.seed = seed
        self.faults = parse_fault_spec(spec)
        self._rng = random.Random(seed)

    @staticmethod
    def from_flags() -> Optional["FaultInjector"]:
        """Build the injector `FLAGS_fault_spec` asks for (None when the
        flag is empty — the production default)."""
        from .flags import flag

        spec = flag("FLAGS_fault_spec")
        return FaultInjector(spec) if spec else None

    def reset(self):
        for f in self.faults:
            f.fired = False
        self._rng = random.Random(self.seed)
        return self

    def pending(self) -> List[Fault]:
        return [f for f in self.faults if not f.fired]

    def fired(self) -> List[Fault]:
        return [f for f in self.faults if f.fired]

    def summary(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.faults:
            if f.fired:
                out[f.kind] = out.get(f.kind, 0) + 1
        return out

    # -- hooks -------------------------------------------------------------
    def _take(self, kind: str, at: int) -> Optional[Fault]:
        for f in self.faults:
            if f.kind == kind and f.at == at and not f.fired:
                f.fired = True
                _MON.counter(f"faults.{kind}").inc()
                return f
        return None

    def on_batch(self, batch_index: int, feed):
        """Called with every raw batch pulled from the loader; raises
        DataError for a scheduled bad batch (simulating a record the
        parser rejects)."""
        if self._take("bad_batch", batch_index) is not None:
            raise DataError(f"injected bad batch {batch_index}",
                            batch_index=batch_index, phase="loader")
        return feed

    def on_feed(self, step: int, feed: dict) -> dict:
        """Called with the feed about to become train step `step`; plants
        a NaN in the first floating-point array so the NaN reaches the
        loss through the real computation (not a mocked check)."""
        if self._take("nan", step) is None:
            return feed
        feed = dict(feed)
        for name in sorted(feed):
            a = np.asarray(feed[name])
            if np.issubdtype(a.dtype, np.floating) and a.size:
                a = a.copy()
                a.flat[self._rng.randrange(a.size)] = np.nan
                feed[name] = a
                break
        else:
            raise ValueError(f"nan@{step}: feed has no floating-point array "
                             f"to poison (names: {sorted(feed)})")
        return feed

    def on_dispatch(self, step: int):
        """Called just before train step `step` is dispatched; raises the
        scheduled transient device error, or delivers a real SIGTERM (the
        preemption notice) to this process."""
        f = self._take("device", step)
        if f is not None:
            code = f.arg or "UNAVAILABLE"
            raise TransientDeviceError(
                f"injected device failure ({code}) at dispatch {step}",
                code=code, step=step, phase="device")
        if self._take("preempt", step) is not None:
            os.kill(os.getpid(), signal.SIGTERM)
