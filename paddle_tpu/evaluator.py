"""fluid.evaluator compatibility (reference python/paddle/fluid/evaluator.py
— the deprecated pre-metrics API; each class points at its fluid.metrics
replacement, which is exactly what the reference's deprecation notices do)."""
from .metrics import (  # noqa: F401
    Accuracy,
    Auc,
    CompositeMetric,
    EditDistance,
    Precision,
    Recall,
)


class ChunkEvaluator:
    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "ChunkEvaluator: chunk-eval (NER span F1) is not implemented; "
            "compute spans host-side from fetched predictions")
