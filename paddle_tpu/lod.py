"""LoD (ragged sequence) support — the TPU-native answer to LoDTensor.

Reference: `framework/lod_tensor.h:58` (LoD = nested offset vectors) and
`:110` (LoDTensor = tensor + LoD).  The reference keeps batches *flat*
(shape [sum_len, ...] + offset table) and every sequence kernel walks the
offsets.  A static-shape compiler wants the opposite: **padded dense
[batch, max_len, ...] + a lengths vector**, with masks derived inside the
compiled program (SURVEY.md §5.7/§7.8).  This module is that boundary:

  * `LoDTensor` — host-side ragged container (list of per-sequence numpy
    arrays).  `.padded(bucket=...)` produces (padded, lengths) with the
    time axis bucketed (rounded up to a multiple / power of two) so feed
    shape drift doesn't trigger a recompile per distinct max_len.
  * `create_lod_tensor(data, recursive_seq_lens, place)` — reference API
    (`lod_tensor.py:create_lod_tensor`) accepting the flat layout and
    converting to ragged.

Inside a Program, a ragged variable `x` (lod_level >= 1) is TWO arrays:
`x` (padded) and `x@LOD` (int32 valid lengths, shape [batch]).  The
executor feeds both when the user feeds a `LoDTensor`; sequence ops take
the lengths as an explicit input slot and lower to masked dense compute.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

LOD_SUFFIX = "@LOD"

# Default time-axis bucketing policy: round max_len up to a multiple of
# _BUCKET_MULTIPLE, then to the next power of two once past _POW2_FROM.
# Bounds distinct compiled shapes to O(log max_len) (SURVEY §7 hard part 6).
_BUCKET_MULTIPLE = 8
_POW2_FROM = 64


def bucket_length(n: int) -> int:
    """Smallest bucketed length >= n under the default policy."""
    n = max(int(n), 1)
    if n <= _POW2_FROM:
        return -(-n // _BUCKET_MULTIPLE) * _BUCKET_MULTIPLE
    b = _POW2_FROM
    while b < n:
        b *= 2
    return b


def lod_var_name(name: str) -> str:
    return name + LOD_SUFFIX


class LoDTensor:
    """Host-side ragged batch: a list of per-sequence numpy arrays.

    Each sequence has shape [len_i, *feature]; `padded()` stacks them into
    [batch, bucket(max_len), *feature] plus an int32 lengths vector.
    """

    def __init__(self, sequences: Sequence[np.ndarray], dtype=None):
        seqs = [np.asarray(s) for s in sequences]
        if not seqs:
            raise ValueError("LoDTensor needs at least one sequence")
        feat = seqs[0].shape[1:]
        for s in seqs:
            if s.shape[1:] != feat:
                raise ValueError(
                    f"ragged sequences must share feature dims: {s.shape[1:]} vs {feat}"
                )
        if dtype is not None:
            seqs = [s.astype(dtype) for s in seqs]
        self.sequences = seqs

    # --- reference pybind LoDTensor surface -------------------------------
    def lod(self):
        """offset-style LoD table [[0, l1, l1+l2, ...]] (reference
        LoDTensor.lod)."""
        offs = [0]
        for s in self.sequences:
            offs.append(offs[-1] + len(s))
        return [offs]

    def set_lod(self, lod):
        """re-segment the flat payload by an offset table."""
        flat = np.concatenate(self.sequences, axis=0)
        offs = lod[0]
        self.sequences = [flat[offs[i]:offs[i + 1]]
                          for i in range(len(offs) - 1)]

    def set_recursive_sequence_lengths(self, lengths):
        flat = np.concatenate(self.sequences, axis=0)
        out, pos = [], 0
        for ln in lengths[0]:
            out.append(flat[pos:pos + ln])
            pos += ln
        self.sequences = out

    def has_valid_recursive_sequence_lengths(self):
        """structurally valid: at least one sequence and consistent feature
        dims (the offset-table monotonicity of the reference is implied by
        the list-of-arrays representation)."""
        if not self.sequences:
            return False
        feat = self.sequences[0].shape[1:]
        return all(s.shape[1:] == feat for s in self.sequences)

    def shape(self):
        total = sum(len(s) for s in self.sequences)
        return (total,) + tuple(self.sequences[0].shape[1:])

    def __len__(self):
        return len(self.sequences)

    @property
    def lengths(self) -> np.ndarray:
        return np.array([len(s) for s in self.sequences], dtype=np.int32)

    @property
    def dtype(self):
        return self.sequences[0].dtype

    def padded(self, bucket: Union[bool, int] = True):
        """Returns (padded [b, T, *f], lengths [b] int32).

        bucket=True applies the default bucketing policy to max_len;
        bucket=<int> pads the time axis to exactly that length;
        bucket=False pads to the exact max_len.
        """
        lens = self.lengths
        max_len = int(lens.max())
        if bucket is True:
            T = bucket_length(max_len)
        elif bucket is False:
            T = max_len
        else:
            T = int(bucket)
            if T < max_len:
                raise ValueError(f"bucket {T} < longest sequence {max_len}")
        feat = self.sequences[0].shape[1:]
        out = np.zeros((len(self.sequences), T) + tuple(feat), dtype=self.dtype)
        for i, s in enumerate(self.sequences):
            out[i, : len(s)] = s
        return out, lens

    @staticmethod
    def from_padded(padded: np.ndarray, lengths: Sequence[int]) -> "LoDTensor":
        return LoDTensor([padded[i, : int(l)] for i, l in enumerate(lengths)])

    def recursive_sequence_lengths(self) -> List[List[int]]:
        """Reference LoDTensor API (length-based LoD, one level)."""
        return [[int(l) for l in self.lengths]]

    def __repr__(self):
        return f"LoDTensor(batch={len(self)}, lengths={self.lengths.tolist()})"


def create_lod_tensor(data, recursive_seq_lens, place=None) -> LoDTensor:
    """Reference `fluid.create_lod_tensor` (lod_tensor.py): build a ragged
    batch from a flat array + length-based LoD (one level supported; deeper
    nesting flattens outer levels, matching how sequence ops consume it)."""
    if isinstance(data, LoDTensor):
        return data
    if isinstance(data, (list, tuple)) and not isinstance(data[0], (int, float)):
        arrs = [np.asarray(s) for s in data]
        if all(a.ndim >= 1 for a in arrs):
            return LoDTensor(arrs)
    flat = np.asarray(data)
    lens = list(recursive_seq_lens[-1])
    if sum(lens) != flat.shape[0]:
        raise ValueError(
            f"sum of seq lens {sum(lens)} != leading dim {flat.shape[0]}"
        )
    seqs = []
    off = 0
    for l in lens:
        seqs.append(flat[off : off + l])
        off += l
    return LoDTensor(seqs)


class LoDTensorArray(list):
    """reference pybind LoDTensorArray: a python list of LoDTensors."""

    def append(self, t):  # noqa: A003 - reference name
        list.append(self, t)
