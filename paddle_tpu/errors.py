"""Failure taxonomy for fault-tolerant training.

Long accelerator runs do not die from clean exits: they die from bad
records, numeric blow-ups, transient XLA/runtime failures, and pod
preemptions.  The reference runtime surfaced all of these as whatever
exception the failing layer happened to raise; nothing downstream could
tell "skip this batch" from "the program is miscompiled".  This module is
the shared vocabulary the resilience layer (paddle_tpu/resilience.py)
routes on:

    DataError             a batch the input pipeline could not produce or
                          parse — skippable within a budget
    NumericError          the FLAGS_check_nan_inf guard tripped (NaN/Inf
                          in a fetched value) — skippable / rollbackable
    TransientDeviceError  runtime failure the next attempt may not see
                          (XLA RESOURCE_EXHAUSTED / UNAVAILABLE / ...) —
                          retryable with backoff
    PreemptionError       the pod is going away — flush a checkpoint and
                          exit resumable
    FatalError            everything else — never retried
    LockTimeoutError      a named-lock acquisition blew FLAGS_lock_timeout_s
                          (core/locks.py) — names BOTH the wanted lock and
                          every lock the thread holds, with their declared
                          ranks, instead of hanging the worker forever —
                          never retried (the lock order is wrong, not the
                          run)
    ResourceError         the static resource planner predicts the program
                          cannot fit in device HBM (phase=build, raised
                          before any XLA compile/allocate, naming the ops
                          at the predicted peak) — never retried
    CheckpointError       a checkpoint that must not be loaded as asked
                          (world-size mismatch without elastic opt-in,
                          inconsistent rank cursors) — never retried
    StorageError          the storage layer itself failed an I/O operation
                          (phase="storage", routed through the io.py choke
                          point): TRANSIENT errnos (ENOSPC/EIO/EAGAIN/
                          ETIMEDOUT — a filling disk, a flaky NFS mount, a
                          throttled object store) are retried with seeded
                          backoff and, for checkpoints, degrade to
                          lag-bounded unprotected training instead of
                          killing the worker; TERMINAL errnos (EROFS/
                          EACCES) skip straight to the fallback dir /
                          degraded mode — no retry changes a read-only
                          mount
    IntegrityError        wrong-but-FINITE state (paddle_tpu/integrity.py):
                          a live cross-rank digest divergence named a
                          corrupt rank, or an at-rest sha256 in a
                          checkpoint/model manifest failed verification.
                          Recoverable when a clean COMMITTED checkpoint
                          predates the corruption window — the resilient
                          loop rolls back (restore + exact RNG/cursor
                          rewind) instead of training forward on corrupt
                          state; otherwise terminal
    ServingError          the serving runtime (paddle_tpu/serving/)
                          refused or failed a request/control action on
                          purpose: admission control shed it, its deadline
                          expired, a published snapshot failed
                          verification, or a model load would blow the
                          HBM budget.  `reason` carries the stable
                          machine-readable code clients route on

and, for the multi-worker tier (paddle_tpu/dist_resilience.py):

    DistributedError      base of the gang-level failures below — one
                          worker cannot fix these alone; the gang-restart
                          driver (paddle_tpu/launch.py) owns recovery
    PeerFailureError      a peer worker stopped heartbeating (crashed,
                          SIGKILLed, wedged) while this worker was inside
                          or about to enter a collective
    CollectiveTimeoutError a collective/barrier blew its armed deadline
                          with every peer still heartbeating (deadlocked
                          collective, pathological straggler)

Every class subclasses RuntimeError so legacy call sites catching
RuntimeError (the NaN guard's historical type) keep working.

`classify(exc)` maps an arbitrary exception onto this taxonomy, reading
context breadcrumbs (`attach_context`) that the executor's sticky
resolution errors, the pipeline's drain path, and the loader's producer
thread leave on exceptions they forward.
"""
from __future__ import annotations

__all__ = ["TrainingError", "DataError", "NumericError",
           "TransientDeviceError", "PreemptionError", "FatalError",
           "CheckpointError", "ServingError", "ResourceError",
           "LockTimeoutError", "IntegrityError", "StorageError",
           "DistributedError", "PeerFailureError", "CollectiveTimeoutError",
           "ParamServerError",
           "classify", "attach_context", "get_context",
           "TRANSIENT_STORAGE_ERRNOS", "TERMINAL_STORAGE_ERRNOS",
           "TRANSIENT_PS_ERRNOS"]

import errno as _errno
from typing import Optional

# The storage-failure split (ISSUE 15).  Transient: the next attempt may
# not see it (space is being freed, the mount is flapping, the store is
# throttling).  Terminal: retrying cannot help — the filesystem is
# read-only or the credentials are wrong; only a different destination
# (FLAGS_ckpt_fallback_dir) or an operator can.
TRANSIENT_STORAGE_ERRNOS = (_errno.ENOSPC, _errno.EIO, _errno.EAGAIN,
                            _errno.ETIMEDOUT)
TERMINAL_STORAGE_ERRNOS = (_errno.EROFS, _errno.EACCES)

# The pserver-failure split (ISSUE 19).  Transient: the socket died
# because the pserver process did (its supervisor is restarting it) or
# the network flapped — reconnect + retry is the answer.  A socket
# TimeoutError maps transient too (KVClient checks the type, not just
# the errno).  Anything else on the wire — protocol violations above
# all — is terminal.
TRANSIENT_PS_ERRNOS = (_errno.ECONNREFUSED, _errno.ECONNRESET,
                       _errno.ECONNABORTED, _errno.EPIPE,
                       _errno.ETIMEDOUT, _errno.EAGAIN,
                       _errno.EHOSTUNREACH)


class TrainingError(RuntimeError):
    """Base of the failure taxonomy.  Carries structured context — which
    train step / raw batch / layer the failure belongs to — so recovery
    can rewind to exactly the right point."""

    def __init__(self, message: str, *, step: Optional[int] = None,
                 batch_index: Optional[int] = None,
                 phase: Optional[str] = None):
        super().__init__(message)
        self.step = step
        self.batch_index = batch_index
        self.phase = phase

    def __str__(self):
        base = super().__str__()
        ctx = []
        if self.step is not None:
            ctx.append(f"step={self.step}")
        if self.batch_index is not None:
            ctx.append(f"batch={self.batch_index}")
        if self.phase:
            ctx.append(f"phase={self.phase}")
        return f"{base} [{', '.join(ctx)}]" if ctx else base


class DataError(TrainingError):
    """The input pipeline failed to produce a batch (parse error, corrupt
    record, injected bad batch).  Dropping the batch is sound; the
    resilient loop does so within `RetryPolicy.max_bad_batches`."""


class NumericError(TrainingError):
    """NaN/Inf reached a fetched value (the FLAGS_check_nan_inf guard).
    Since the step that produced it already wrote its (poisoned) update
    into the scope, recovery needs state restore, not just retry — see
    `resilient_train_loop`'s `nan_mode`."""


class TransientDeviceError(TrainingError):
    """Device/runtime failure a later attempt may not reproduce: XLA
    RESOURCE_EXHAUSTED (HBM pressure), UNAVAILABLE / ABORTED (tunnel or
    runtime hiccup), DEADLINE_EXCEEDED.  `resource_exhausted` marks the
    OOM flavor so the resilient loop can also shed in-flight depth."""

    def __init__(self, message: str, *, code: Optional[str] = None,
                 resource_exhausted: bool = False, **kw):
        super().__init__(message, **kw)
        self.code = code
        self.resource_exhausted = bool(resource_exhausted
                                       or code == "RESOURCE_EXHAUSTED")


class PreemptionError(TrainingError):
    """The process received its preemption notice (SIGTERM on TPU pods).
    Not an error to retry: flush a checkpoint, report where to resume."""


class FatalError(TrainingError):
    """Anything `classify` cannot place in a recoverable class: program
    bugs, INVALID_ARGUMENT compiles, user-code exceptions.  The resilient
    loop re-raises these untouched."""


class LockTimeoutError(FatalError):
    """A `locks.named_lock` acquisition did not complete within
    `FLAGS_lock_timeout_s` (core/locks.py).  A correctly ordered lock
    graph cannot deadlock, so a blown lock deadline means either a
    genuine deadlock (an acquisition path the concurrency lint did not
    see inverted the declared ranks) or a critical section holding a hot
    lock across blocking work — both program bugs, never retried.  The
    message and fields name BOTH sides: `wanted`/`wanted_rank` is the
    lock that timed out, `held` the [(name, rank), ...] this thread
    already holds — exactly what a deadlock report needs, captured while
    there is still a Python stack to read instead of a wedged worker to
    SIGKILL."""

    def __init__(self, message: str, *, wanted: Optional[str] = None,
                 wanted_rank: Optional[int] = None, held=None,
                 timeout_s: Optional[float] = None, **kw):
        kw.setdefault("phase", "locking")
        super().__init__(message, **kw)
        self.wanted = wanted
        self.wanted_rank = wanted_rank
        self.held = list(held or [])
        self.timeout_s = timeout_s


class ResourceError(FatalError):
    """The static resource planner (core/resource_plan.py) predicts the
    program cannot run within the device's HBM: the liveness-based
    peak-memory estimate exceeds the known limit.  Raised at compile-cache
    miss time, BEFORE any XLA compile or device allocation — the point is
    to name the ops and buffers at the predicted peak (`watermark_ops`)
    while there is still a Python stack to read, instead of an opaque
    allocator RESOURCE_EXHAUSTED mid-compile.  phase="build"; never
    retried (the program itself is too big, not the run — shrink the
    batch, enable remat/BuildStrategy.memory_optimize, or shard).

    Distinct from `TransientDeviceError(resource_exhausted=True)`: that is
    the RUNTIME allocator actually failing (fragmentation, co-residency),
    which a retry at lower in-flight depth may survive; this is a static
    prediction that no retry changes."""

    def __init__(self, message: str, *, needed_bytes: Optional[int] = None,
                 limit_bytes: Optional[int] = None, watermark_ops=None, **kw):
        kw.setdefault("phase", "build")
        super().__init__(message, **kw)
        self.needed_bytes = needed_bytes
        self.limit_bytes = limit_bytes
        self.watermark_ops = list(watermark_ops or [])


class CheckpointError(TrainingError):
    """A checkpoint cannot be safely loaded as asked: the saved world size
    does not match the restoring gang (and the caller did not opt into
    elastic re-sharding), rank cursors are mutually inconsistent, or the
    on-disk layout contradicts its own manifest.  Never retried — loading
    anyway would misposition shards or double-train data, which is worse
    than dying loudly.  `saved_world` / `current_world` carry the two
    sizes when a world-size mismatch is the cause."""

    def __init__(self, message: str, *, saved_world: Optional[int] = None,
                 current_world: Optional[int] = None, **kw):
        kw.setdefault("phase", "checkpoint")
        super().__init__(message, **kw)
        self.saved_world = saved_world
        self.current_world = current_world


class StorageError(TrainingError):
    """The storage layer failed an I/O operation (phase="storage" — every
    checkpoint/manifest/sidecar/model-store byte crosses the `paddle_tpu.
    io` choke point, which stamps the breadcrumb).  The `transient` bit is
    the routing decision the whole resilience tier keys on:

      * transient (ENOSPC, EIO, EAGAIN, ETIMEDOUT): retried with seeded
        backoff (`RetryPolicy.max_storage_retries`); a checkpoint save
        that exhausts its retries enters DEGRADED MODE — training
        continues, `resilience.ckpt_lag_steps` rises, and a bounded lag
        (`FLAGS_max_ckpt_lag_steps`) converts to this error re-raised
        terminal, so unprotected training cannot run forever;
      * terminal (EROFS, EACCES): retries are skipped — the fallback dir
        (`FLAGS_ckpt_fallback_dir`) is tried, then degraded mode.

    `op` is "read"/"write", `path` the failing file, `errno` the OS code
    (mirrors OSError).  A transient publish-source failure retries WITHOUT
    quarantining the snapshot (serving/publisher.py) — flaky I/O is not
    evidence of rot."""

    def __init__(self, message: str, *, op: Optional[str] = None,
                 path: Optional[str] = None, errno: Optional[int] = None,
                 transient: Optional[bool] = None, **kw):
        kw.setdefault("phase", "storage")
        super().__init__(message, **kw)
        self.op = op
        self.path = path
        self.errno = errno
        if transient is None:
            transient = errno in TRANSIENT_STORAGE_ERRNOS
        self.transient = bool(transient)

    def __str__(self):
        base = super().__str__()
        ctx = []
        if self.op:
            ctx.append(f"op={self.op}")
        if self.errno is not None:
            ctx.append(f"errno={_errno.errorcode.get(self.errno, self.errno)}")
        ctx.append("transient" if self.transient else "terminal")
        if self.path:
            ctx.append(f"path={self.path}")
        return f"{base} [{', '.join(ctx)}]"


class ParamServerError(TrainingError):
    """The host sparse-table tier (paddle_tpu/param_server.py) failed an
    RPC — the parameter-server mirror of `StorageError`, with the same
    transient/terminal split the resilience tier keys on:

      * transient (connection refused/reset, broken pipe, socket
        timeout, host unreachable): the pserver died or is being
        crash-restarted by its supervisor; the KVClient retries with
        reconnect + seeded backoff (`FLAGS_ps_retries`) and — because
        every push carries a per-client sequence number the server
        dedups — a retried sparse push applies EXACTLY once.  When the
        retry budget is exhausted, training enters bounded degraded
        mode (hot-shard-only steps, `sparse.host_lag_steps` gauge)
        instead of wedging;
      * terminal (protocol violation: bad magic, frame past
        `FLAGS_ps_max_frame_mb`, exhausted degraded-mode budget past
        `FLAGS_max_host_lag_steps`): retrying cannot help — the wire is
        corrupt or the contract is broken.

    `op` is the protocol op ("pull"/"push"/"create"/"fetch"/...),
    `endpoint` the pserver address, `errno` the OS code when an OSError
    is behind it."""

    def __init__(self, message: str, *, op: Optional[str] = None,
                 endpoint: Optional[str] = None,
                 errno: Optional[int] = None,
                 transient: Optional[bool] = None, **kw):
        kw.setdefault("phase", "pserver")
        super().__init__(message, **kw)
        self.op = op
        self.endpoint = endpoint
        self.errno = errno
        if transient is None:
            transient = errno in TRANSIENT_PS_ERRNOS
        self.transient = bool(transient)

    def __str__(self):
        base = super().__str__()
        ctx = []
        if self.op:
            ctx.append(f"op={self.op}")
        if self.errno is not None:
            ctx.append(f"errno={_errno.errorcode.get(self.errno, self.errno)}")
        ctx.append("transient" if self.transient else "terminal")
        if self.endpoint:
            ctx.append(f"endpoint={self.endpoint}")
        return f"{base} [{', '.join(ctx)}]"


class IntegrityError(TrainingError):
    """Silent data corruption made loud (paddle_tpu/integrity.py): state
    that is wrong but FINITE, which no NaN guard, CRC, or structure check
    can see.  Two sources:

      * a LIVE digest divergence — replicated dp state stopped agreeing
        bit-exactly across ranks.  `corrupt_ranks` names the voted
        offender(s) (`attributed=False` when the vote tied and the value
        plausibility tiebreak could not break it — e.g. a low-mantissa
        flip on a 2-rank gang), and `safe_step` is the newest step the
        digests PROVE clean: the resilient loop's rollback must restore a
        checkpoint at or before it (a later checkpoint may have committed
        the corruption);
      * an AT-REST digest mismatch — a file named by a checkpoint or
        inference-model manifest no longer hashes to its recorded sha256
        (`file` / `expected` / `actual`).  Restore walks back past it,
        publish quarantines it.

    Recoverable via rollback when a clean committed checkpoint exists;
    never "retried" in place — the in-memory (or on-disk) state itself is
    poison."""

    def __init__(self, message: str, *, corrupt_ranks=None,
                 attributed: bool = True, safe_step: Optional[int] = None,
                 file: Optional[str] = None, expected: Optional[str] = None,
                 actual: Optional[str] = None, **kw):
        kw.setdefault("phase", "integrity")
        super().__init__(message, **kw)
        self.corrupt_ranks = list(corrupt_ranks or [])
        self.attributed = bool(attributed)
        self.safe_step = safe_step
        self.file = file
        self.expected = expected
        self.actual = actual

    def __str__(self):
        base = super().__str__()
        ctx = []
        if self.corrupt_ranks:
            ctx.append(f"corrupt_ranks={self.corrupt_ranks}"
                       + ("" if self.attributed else " (unattributed)"))
        if self.safe_step is not None:
            ctx.append(f"safe_step={self.safe_step}")
        if self.file:
            ctx.append(f"file={self.file}")
        return f"{base} [{', '.join(ctx)}]" if ctx else base


class ServingError(TrainingError):
    """The serving runtime (paddle_tpu/serving/) refused or failed a
    request or control action BY DESIGN — these are the classified,
    expected failures that keep an overloaded or mid-reload server
    degrading gracefully instead of wedging:

        reason="overload"          admission control shed the request (the
                                   bounded queue was full; serving it would
                                   grow latency without bound)
        reason="timeout"           the request's deadline expired before a
                                   batch picked it up
        reason="oversize"          the request carries more rows than the
                                   largest compiled bucket; split it
        reason="bad_request"       the request itself is malformed (empty,
                                   scalar or mismatched batch dims, feed
                                   names/shapes off the model's contract) —
                                   rejected at admission so it can never
                                   poison the batch it would join
        reason="publish_rejected"  a staged snapshot failed verification
                                   (torn/corrupt files, program verifier,
                                   NaN weights, golden-smoke failure) and
                                   was quarantined — the old model keeps
                                   serving
        reason="publish_io"        transient STORE I/O (EIO/timeout while
                                   hashing or staging) exhausted the
                                   publish retry budget — the snapshot is
                                   NOT quarantined (flaky I/O is not
                                   evidence of rot); retry when the store
                                   settles
        reason="hbm_budget"        loading the model would exceed the HBM
                                   budget and eviction could not free
                                   enough
        reason="model_missing"     no model under that name (never loaded,
                                   unloaded, or evicted)
        reason="shutdown"          the server is draining/stopped
        reason="replica_down"      fleet routing (serving/router.py): the
                                   replica carrying this in-flight request
                                   died mid-request, or — for NEW traffic —
                                   no healthy replica remains to dispatch
                                   to.  New traffic only sees this when the
                                   whole fleet is down; a single replica
                                   death costs exactly its own in-flight
                                   requests and redistributes the rest
                                   within one heartbeat miss window
        reason="roll_halted"       a fleet rolling publish halted (a verify
                                   rung failed on some replica, or a
                                   replica lost mid-roll could not be
                                   recovered) and the fleet was converged
                                   back onto the last good version

    Never retried blindly: "overload"/"timeout" are backpressure the
    CLIENT routes on (retry elsewhere, degrade, drop); the rest are
    operator-facing.  `model` names the model involved, when any."""

    def __init__(self, message: str, *, reason: Optional[str] = None,
                 model: Optional[str] = None,
                 trace_id: Optional[str] = None, **kw):
        kw.setdefault("phase", "serving")
        super().__init__(message, **kw)
        self.reason = reason
        self.model = model
        # the request-flight trace id (serving/tracing.py) when the monitor
        # was on: the error a CLIENT caught names the exact trace
        # `serve_trace --request <id>` renders.  None with the monitor off.
        self.trace_id = trace_id

    def __str__(self):
        base = super().__str__()
        ctx = []
        if self.reason:
            ctx.append(f"reason={self.reason}")
        if self.model:
            ctx.append(f"model={self.model}")
        if self.trace_id:
            ctx.append(f"trace={self.trace_id}")
        return f"{base} [{', '.join(ctx)}]" if ctx else base


class DistributedError(TrainingError):
    """Base of the gang-level failures.  A single worker cannot recover
    from these (every peer is wedged in the same collective); the point of
    raising instead of hanging is to die LOUDLY and classified, so the
    gang-restart driver (paddle_tpu/launch.py) can kill the stragglers and
    relaunch from the last coordinated checkpoint.  Carries the local rank
    and, where known, the set of implicated peers."""

    def __init__(self, message: str, *, rank: Optional[int] = None,
                 peers=None, collective: Optional[str] = None, **kw):
        super().__init__(message, **kw)
        self.rank = rank
        self.peers = list(peers) if peers is not None else []
        self.collective = collective

    def __str__(self):
        base = super().__str__()
        ctx = []
        if self.rank is not None:
            ctx.append(f"rank={self.rank}")
        if self.peers:
            ctx.append(f"peers={self.peers}")
        if self.collective:
            ctx.append(f"collective={self.collective}")
        return f"{base} [{', '.join(ctx)}]" if ctx else base


class PeerFailureError(DistributedError):
    """A peer worker stopped heartbeating — crashed, OOM-killed, or wedged
    past the liveness deadline.  The next (or current) collective with that
    peer can never complete; the watchdog raises this instead of letting
    the process hang inside it.  `peers` lists the dead ranks."""


class CollectiveTimeoutError(DistributedError):
    """A collective/barrier exceeded its armed deadline while every peer
    still heartbeats: a deadlocked collective, divergent program order, or
    a straggler past the watchdog budget.  Thread stacks were dumped at
    raise time (`dist_resilience.dump_stacks`)."""


# XLA status codes whose failures are worth retrying.  INVALID_ARGUMENT /
# INTERNAL / UNIMPLEMENTED are deliberately absent: those reproduce.
_TRANSIENT_CODES = ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "ABORTED",
                    "DEADLINE_EXCEEDED", "CANCELLED")


def attach_context(exc: BaseException, *, step: Optional[int] = None,
                   batch_index: Optional[int] = None,
                   phase: Optional[str] = None) -> BaseException:
    """Leave step/batch/phase breadcrumbs on an exception without changing
    its type (sticky errors must keep raising as themselves — pinned by
    the loader's propagate-as-itself contract).  First writer wins per
    key, so the layer closest to the failure names it."""
    try:
        ctx = exc.__dict__.setdefault("_pt_ctx", {})
    except AttributeError:  # exceptions with __slots__ / C extensions
        return exc
    for k, v in (("step", step), ("batch_index", batch_index),
                 ("phase", phase)):
        if v is not None and ctx.get(k) is None:
            ctx[k] = v
    if isinstance(exc, TrainingError):
        for k in ("step", "batch_index", "phase"):
            if getattr(exc, k, None) is None and ctx.get(k) is not None:
                setattr(exc, k, ctx[k])
    return exc


def get_context(exc: BaseException) -> dict:
    """The breadcrumbs `attach_context` left (empty dict if none)."""
    ctx = dict(getattr(exc, "_pt_ctx", None) or {})
    if isinstance(exc, TrainingError):
        for k in ("step", "batch_index", "phase"):
            if ctx.get(k) is None and getattr(exc, k, None) is not None:
                ctx[k] = getattr(exc, k)
    return ctx


def _eno_of(exc: BaseException) -> Optional[int]:
    return getattr(exc, "errno", None) if isinstance(exc, OSError) else None


def classify(exc: BaseException, wrap_unknown: bool = False) -> BaseException:
    """Map an exception onto the taxonomy.

    Returns the exception itself when it is already a `TrainingError` or
    when no specific class applies (so sticky errors keep their original
    type unless a mapping genuinely adds information).  With
    `wrap_unknown=True` unmapped exceptions come back wrapped in
    `FatalError` instead.  Mapped exceptions carry the original as
    `__cause__` and inherit any attached step/batch context."""
    if isinstance(exc, TrainingError):
        return exc
    ctx = get_context(exc)
    kw = {"step": ctx.get("step"), "batch_index": ctx.get("batch_index"),
          "phase": ctx.get("phase")}

    def _wrap(cls, **extra):
        e = cls(f"{type(exc).__name__}: {exc}", **kw, **extra)
        e.__cause__ = exc
        return e

    # KeyboardInterrupt / SystemExit are control flow, never classified.
    if not isinstance(exc, Exception):
        return exc
    msg = str(exc)
    # XLA runtime failures (jaxlib XlaRuntimeError subclasses RuntimeError
    # and spells its status code into the message) plus anything else that
    # carries a status-code-shaped message.  Checked BEFORE the loader
    # breadcrumb: an XLA RESOURCE_EXHAUSTED raised while the producer
    # thread stages a batch is an HBM problem, not skippable data.
    if isinstance(exc, (RuntimeError, OSError)):
        for code in _TRANSIENT_CODES:
            if code in msg:
                kw.pop("phase", None)
                return _wrap(TransientDeviceError, code=code, phase="device")
    # Parameter-server failures (ISSUE 19): an exception that crossed the
    # KVClient seam carries phase="pserver" and maps onto the pserver
    # transient/terminal split.  Checked BEFORE storage: a socket
    # ETIMEDOUT shares an errno with the transient STORAGE set, but the
    # verdict (retry the RPC / enter degraded sparse mode) belongs to the
    # pserver tier, not the checkpoint store.
    if ctx.get("phase") == "pserver" and isinstance(
            exc, (OSError, TimeoutError)):
        kw.pop("phase", None)
        transient = (isinstance(exc, TimeoutError)
                     or _eno_of(exc) in TRANSIENT_PS_ERRNOS
                     or isinstance(exc, ConnectionError))
        return _wrap(ParamServerError, errno=_eno_of(exc),
                     transient=transient, phase="pserver")
    # Storage-layer failures (ISSUE 15): an OSError that crossed the io.py
    # choke point carries phase="storage" and maps by errno onto the
    # transient/terminal split.  Checked BEFORE the loader breadcrumb so a
    # checkpoint read failing inside a producer thread stays a storage
    # failure; a bare OSError with a storage errno and NO phase breadcrumb
    # maps too (below, AFTER the loader check — an EIO while producing a
    # data batch is the data layer's problem, handled by its own budget).
    _eno = getattr(exc, "errno", None) if isinstance(exc, OSError) else None
    _storage_errno = _eno in TRANSIENT_STORAGE_ERRNOS \
        or _eno in TERMINAL_STORAGE_ERRNOS
    if _storage_errno and ctx.get("phase") == "storage":
        kw.pop("phase", None)
        return _wrap(StorageError, errno=_eno,
                     path=getattr(exc, "filename", None), phase="storage")
    # Producer-thread breadcrumb: the loader marks exceptions raised while
    # producing a batch, whatever their type (user generator bugs raise as
    # themselves but recovery treats them as data failures).  "feed" is the
    # FeedSpec validation boundary (reader.py): a dtype/shape-mismatched or
    # non-finite feed is a data failure caught before lowering.
    if ctx.get("phase") in ("loader", "feed"):
        return _wrap(DataError)
    if _storage_errno and ctx.get("phase") is None:
        kw.pop("phase", None)
        return _wrap(StorageError, errno=_eno,
                     path=getattr(exc, "filename", None), phase="storage")
    # The NaN/Inf guard's historical RuntimeError message.
    if isinstance(exc, (RuntimeError, FloatingPointError)) and "NaN/Inf" in msg:
        return _wrap(NumericError)
    if wrap_unknown:
        return _wrap(FatalError)
    return exc
