"""Memory gauges: what is HBM (or host RAM on the CPU backend) holding.

All gauges are lazy (`Gauge.set_fn`): they walk `jax.live_arrays()` /
query PJRT `memory_stats()` only when an exporter reads them, never on
the training hot path.
"""
from __future__ import annotations


def _live_arrays():
    import jax

    try:
        return jax.live_arrays()
    except Exception:
        return []


def live_array_bytes() -> int:
    total = 0
    for a in _live_arrays():
        try:
            if a.is_deleted():
                continue
            total += a.nbytes
        except Exception:
            pass
    return total


def live_array_count() -> int:
    n = 0
    for a in _live_arrays():
        try:
            if not a.is_deleted():
                n += 1
        except Exception:
            pass
    return n


def device_bytes_in_use(device_index: int = 0) -> float:
    """PJRT allocator's bytes_in_use for one device; NaN where the backend
    (e.g. XLA:CPU) exposes no memory_stats."""
    import jax

    try:
        dev = jax.local_devices()[device_index]
        stats = dev.memory_stats()
        if stats:
            return float(stats.get("bytes_in_use", float("nan")))
    except Exception:
        pass
    return float("nan")


def register_memory_gauges(mon):
    """Install the lazy memory gauges on a Monitor (idempotent)."""
    mon.gauge("memory.live_array_bytes").set_fn(live_array_bytes)
    mon.gauge("memory.live_array_count").set_fn(live_array_count)
    mon.gauge("memory.device_bytes_in_use").set_fn(device_bytes_in_use)
    return mon
