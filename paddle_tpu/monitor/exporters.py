"""Machine-readable views of Monitor state.

Four formats, one source of truth (monitor.core.Monitor):
  * Prometheus text exposition — counters, gauges, span summaries;
  * JSON snapshot — everything, for tools/perf_report.py render/diff;
  * Chrome trace JSON — the tools/timeline.py role, with per-process
    lanes and span nesting (tid/depth preserved);
  * MonitorLogger — periodic JSONL appender bench tooling consumes
    (tools/perf_report.py --check gates on it in CI).
"""
from __future__ import annotations

import json
import re
import time
from typing import Dict, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
PROM_PREFIX = "paddle_tpu_"


def _prom_name(name: str) -> str:
    return PROM_PREFIX + _NAME_RE.sub("_", name)


def prometheus_text(mon) -> str:
    """Prometheus text exposition format (one page per scrape)."""
    lines = []
    for name, v in mon.counter_values().items():
        p = _prom_name(name)
        lines.append(f"# TYPE {p} counter")
        lines.append(f"{p} {v}")
    for name, v in mon.gauge_values().items():
        p = _prom_name(name)
        lines.append(f"# TYPE {p} gauge")
        lines.append(f"{p} {'NaN' if v != v else v}")
    for name, s in sorted(mon.span_stats().items()):
        p = _prom_name(name)
        lines.append(f"# TYPE {p}_seconds summary")
        lines.append(f"{p}_seconds_count {s['calls']}")
        lines.append(f"{p}_seconds_sum {s['total_s']:.9f}")
        # a summary family only admits _count/_sum/quantiles; max is its
        # own gauge so strict OpenMetrics parsers accept the page
        lines.append(f"# TYPE {p}_max_seconds gauge")
        lines.append(f"{p}_max_seconds {s['max_s']:.9f}")
    return "\n".join(lines) + "\n"


def json_snapshot(mon, include_steps: bool = True) -> dict:
    snap = {
        "kind": "snapshot",
        "ts": time.time(),
        "lane": mon.lane,
        "lane_name": mon.lane_name,
        "counters": mon.counter_values(),
        "gauges": mon.gauge_values(),
        "spans": mon.span_stats(),
    }
    if include_steps:
        snap["steps"] = mon.step_records()
    return snap


def export_json(mon, path: str, include_steps: bool = True) -> str:
    with open(path, "w") as f:
        json.dump(json_snapshot(mon, include_steps), f, indent=1)
    return path


def chrome_trace_events(mon, pid: Optional[int] = None,
                        process_name: Optional[str] = None) -> list:
    pid = mon.lane if pid is None else pid
    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "args": {"name": process_name or mon.lane_name}}]
    for name, ts, dur, tid, depth, args in mon.events():
        ev = {"name": name, "ph": "X", "pid": pid, "tid": tid,
              "ts": ts * 1e6, "dur": dur * 1e6, "cat": "span"}
        if args:
            ev["args"] = {k: str(v) for k, v in args.items()}
        events.append(ev)
    return events


def export_chrome_trace(mon, path: str, pid: Optional[int] = None,
                        process_name: Optional[str] = None) -> int:
    """Write buffered span events as Chrome trace JSON; returns the number
    of span events written (metadata rows excluded), matching the old
    profiler.export_chrome_trace contract."""
    events = chrome_trace_events(mon, pid, process_name)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events) - 1


def merge_chrome_traces(named_paths, out_path: str) -> str:
    """Merge several processes' traces into one timeline, one pid lane per
    input (the reference tool's `trainer1=f1,ps=f2` mode)."""
    merged = []
    items = (list(named_paths.items()) if isinstance(named_paths, dict)
             else list(enumerate(named_paths)))
    for pid, (name, p) in enumerate(items):
        with open(p) as f:
            doc = json.load(f)
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            merged.append(ev)
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": str(name)}})
    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged}, f)
    return out_path


def summary_table(mon, sorted_key: str = "total") -> str:
    """The aggregate span table the old profiler printed from EventList."""
    stats = mon.span_stats()
    keyfn = {
        "total": lambda kv: -kv[1]["total_s"],
        "calls": lambda kv: -kv[1]["calls"],
        "max": lambda kv: -kv[1]["max_s"],
        "min": lambda kv: kv[1]["min_s"],
        "ave": lambda kv: -(kv[1]["total_s"] / max(kv[1]["calls"], 1)),
    }.get(sorted_key, lambda kv: -kv[1]["total_s"])
    lines = [
        f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>10} {'Max(ms)':>10} {'Min(ms)':>10}"
    ]
    for tag, r in sorted(stats.items(), key=keyfn):
        avg = r["total_s"] / max(r["calls"], 1)
        lines.append(
            f"{tag:<40} {r['calls']:>8} {r['total_s']*1e3:>12.3f} {avg*1e3:>10.3f} "
            f"{r['max_s']*1e3:>10.3f} {r['min_s']*1e3:>10.3f}"
        )
    return "\n".join(lines)


class MonitorLogger:
    """Appends JSONL records for bench tooling: every `every`-th step
    record as it happens, plus full snapshots on demand.

        logger = monitor.attach_logger(MonitorLogger("metrics.jsonl"))
        ... train ...
        logger.write_snapshot()   # final counter/gauge state
        monitor.detach_logger(logger)
    """

    def __init__(self, path: str, every: int = 1):
        self.path = path
        self.every = max(int(every), 1)
        self._n = 0
        self._mon = None  # set by Monitor.attach_logger callers via bind
        self._fh = None   # persistent append handle: one write+flush per
        # record instead of open/close syscalls on every training step

    def bind(self, mon):
        self._mon = mon
        return self

    def _file(self):
        if self._fh is None or self._fh.closed:
            self._fh = open(self.path, "a")
        return self._fh

    def close(self):
        if self._fh is not None and not self._fh.closed:
            self._fh.close()

    def on_step(self, record: dict):
        self._n += 1
        if self._n % self.every:
            return
        f = self._file()
        f.write(json.dumps(record, default=str) + "\n")
        f.flush()

    def write_snapshot(self, mon=None):
        mon = mon or self._mon
        if mon is None:
            from . import MONITOR

            mon = MONITOR
        f = self._file()
        f.write(json.dumps(json_snapshot(mon, include_steps=False),
                           default=str) + "\n")
        f.flush()
        return self.path
