"""Machine-readable views of Monitor state.

Four formats, one source of truth (monitor.core.Monitor):
  * Prometheus text exposition — counters, gauges, span summaries;
  * JSON snapshot — everything, for tools/perf_report.py render/diff;
  * Chrome trace JSON — the tools/timeline.py role, with per-process
    lanes and span nesting (tid/depth preserved);
  * MonitorLogger — periodic JSONL appender bench tooling consumes
    (tools/perf_report.py --check gates on it in CI).
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
PROM_PREFIX = "paddle_tpu_"


def _prom_name(name: str) -> str:
    """Sanitize an arbitrary span/counter/gauge name into a legal metric
    name ([a-zA-Z_:][a-zA-Z0-9_:]*): every illegal character becomes `_`,
    and the PROM_PREFIX guarantees a legal leading character even for
    names that start with a digit.  Collisions (two raw names mapping to
    one family) are disambiguated at emission with a `raw` label."""
    return PROM_PREFIX + _NAME_RE.sub("_", str(name))


def escape_label_value(v) -> str:
    r"""Escape a label value per the exposition format: backslash, double
    quote, and newline must be written as \\, \", and \n."""
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _label_key(k) -> str:
    """Sanitize a label NAME ([a-zA-Z_][a-zA-Z0-9_]*): illegal characters
    become `_`, and a leading digit gets a `_` prefix (label names have
    no PROM_PREFIX to fix their first character the way metric names do)."""
    s = _NAME_RE.sub("_", str(k)) or "_"
    return "_" + s if s[0].isdigit() else s


def _label_str(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_label_key(k)}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def prometheus_text(mon, labels=None) -> str:
    """Prometheus text exposition format (one page per scrape).

    Hardened (ISSUE 8): metric names are sanitized, `labels` (e.g.
    {"rank": 0}, what a multi-rank scrape endpoint stamps per worker) are
    escaped per the format, a family's TYPE line is emitted exactly once,
    and when two raw names sanitize to the same family the later samples
    carry a `raw="<original>"` label instead of emitting an invalid
    duplicate series."""
    base = _label_str(labels)
    lines = []
    seen_types = set()
    family_raw: Dict[str, str] = {}

    def emit(family: str, typ: str, raw: str, suffix: str, value: str):
        first = family_raw.setdefault(family, raw)
        if family not in seen_types:
            seen_types.add(family)
            lines.append(f"# TYPE {family} {typ}")
        lab = base
        if first != raw:  # sanitization collision: disambiguate the series
            extra = f'raw="{escape_label_value(raw)}"'
            lab = base[:-1] + "," + extra + "}" if base else "{" + extra + "}"
        lines.append(f"{family}{suffix}{lab} {value}")

    for name, v in mon.counter_values().items():
        emit(_prom_name(name), "counter", name, "", str(v))
    for name, v in mon.gauge_values().items():
        emit(_prom_name(name), "gauge", name, "", "NaN" if v != v else str(v))
    for name, s in sorted(mon.span_stats().items()):
        p = _prom_name(name)
        # a summary family only admits _count/_sum/quantiles; max is its
        # own gauge so strict OpenMetrics parsers accept the page
        emit(p + "_seconds", "summary", name, "_count", str(s["calls"]))
        emit(p + "_seconds", "summary", name, "_sum", f"{s['total_s']:.9f}")
        emit(p + "_max_seconds", "gauge", name, "", f"{s['max_s']:.9f}")
    return "\n".join(lines) + "\n"


def json_snapshot(mon, include_steps: bool = True) -> dict:
    snap = {
        "kind": "snapshot",
        "ts": time.time(),
        "lane": mon.lane,
        "lane_name": mon.lane_name,
        "counters": mon.counter_values(),
        "gauges": mon.gauge_values(),
        "spans": mon.span_stats(),
    }
    if include_steps:
        snap["steps"] = mon.step_records()
    return snap


def export_json(mon, path: str, include_steps: bool = True) -> str:
    with open(path, "w") as f:
        json.dump(json_snapshot(mon, include_steps), f, indent=1)
    return path


def request_trace_events(mon, pid: Optional[int] = None) -> list:
    """Render the monitor's request-flight traces (ISSUE 16, the bounded
    ring serving/tracing.py fills) as Chrome-trace ASYNC lanes: one
    b/e pair per span, correlated by the request's trace id.  Async
    events get their own per-id track in perfetto/chrome://tracing, so
    merging these with the per-rank span lanes (merge_chrome_traces)
    shows a request from submit to respond ABOVE the executor spans that
    served it."""
    pid = mon.lane if pid is None else pid
    events = []
    for tr in getattr(mon, "request_traces", list)() or ():
        rid = str(tr.get("trace_id", "?"))
        t0_us = float(tr.get("ts", 0.0) or 0.0) * 1e6
        spans = tr.get("spans") or ()
        for i, sp in enumerate(spans):
            ts = t0_us + float(sp.get("t_ms", 0.0) or 0.0) * 1e3
            b = {"name": f"req.{sp.get('name', '?')}", "ph": "b",
                 "cat": "request", "id": rid, "pid": pid, "tid": 0,
                 "ts": ts}
            if i == 0:
                b["args"] = {"trace_id": rid,
                             "model": str(tr.get("model", "")),
                             "outcome": str(tr.get("outcome", "")),
                             "reason": str(tr.get("reason", "")),
                             "bucket": str(tr.get("bucket", "")),
                             "pad_rows": str(tr.get("pad_rows", ""))}
            events.append(b)
            events.append({"name": b["name"], "ph": "e", "cat": "request",
                           "id": rid, "pid": pid, "tid": 0,
                           "ts": ts + float(sp.get("dur_ms", 0.0) or 0.0)
                           * 1e3})
    return events


def chrome_trace_events(mon, pid: Optional[int] = None,
                        process_name: Optional[str] = None) -> list:
    pid = mon.lane if pid is None else pid
    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "args": {"name": process_name or mon.lane_name}}]
    for name, ts, dur, tid, depth, args in mon.events():
        ev = {"name": name, "ph": "X", "pid": pid, "tid": tid,
              "ts": ts * 1e6, "dur": dur * 1e6, "cat": "span"}
        if args:
            ev["args"] = {k: str(v) for k, v in args.items()}
        events.append(ev)
    # request-flight lanes ride the same document so one export (and the
    # trace_merge.py gang merge) carries spans AND requests
    events.extend(request_trace_events(mon, pid))
    return events


def export_chrome_trace(mon, path: str, pid: Optional[int] = None,
                        process_name: Optional[str] = None) -> int:
    """Write buffered span events as Chrome trace JSON; returns the number
    of span events written (metadata rows and request-lane async events
    excluded), matching the old profiler.export_chrome_trace contract."""
    events = chrome_trace_events(mon, pid, process_name)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return sum(1 for e in events if e.get("ph") == "X")


def merge_chrome_traces(named_paths, out_path: str) -> str:
    """Merge several processes' traces into one timeline, one pid lane per
    input (the reference tool's `trainer1=f1,ps=f2` mode)."""
    merged = []
    items = (list(named_paths.items()) if isinstance(named_paths, dict)
             else list(enumerate(named_paths)))
    for pid, (name, p) in enumerate(items):
        with open(p) as f:
            doc = json.load(f)
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            merged.append(ev)
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": str(name)}})
    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged}, f)
    return out_path


def summary_table(mon, sorted_key: str = "total") -> str:
    """The aggregate span table the old profiler printed from EventList."""
    stats = mon.span_stats()
    keyfn = {
        "total": lambda kv: -kv[1]["total_s"],
        "calls": lambda kv: -kv[1]["calls"],
        "max": lambda kv: -kv[1]["max_s"],
        "min": lambda kv: kv[1]["min_s"],
        "ave": lambda kv: -(kv[1]["total_s"] / max(kv[1]["calls"], 1)),
    }.get(sorted_key, lambda kv: -kv[1]["total_s"])
    lines = [
        f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>10} {'Max(ms)':>10} {'Min(ms)':>10}"
    ]
    for tag, r in sorted(stats.items(), key=keyfn):
        avg = r["total_s"] / max(r["calls"], 1)
        lines.append(
            f"{tag:<40} {r['calls']:>8} {r['total_s']*1e3:>12.3f} {avg*1e3:>10.3f} "
            f"{r['max_s']*1e3:>10.3f} {r['min_s']*1e3:>10.3f}"
        )
    return "\n".join(lines)


class MonitorLogger:
    """Appends JSONL records for bench tooling: every `every`-th step
    record as it happens, plus full snapshots on demand.

        logger = monitor.attach_logger(MonitorLogger("metrics.jsonl"))
        ... train ...
        logger.write_snapshot()   # final counter/gauge state
        monitor.detach_logger(logger)
    """

    def __init__(self, path: str, every: int = 1):
        from ..core.locks import named_lock

        self.path = path
        self.every = max(int(every), 1)
        self._n = 0
        self._mon = None  # set by Monitor.attach_logger callers via bind
        self._fh = None   # persistent append handle: one write+flush per
        # record instead of open/close syscalls on every training step
        # records arrive from more than one thread (the heartbeat thread
        # emits dist_events, the training thread emits steps); a lock keeps
        # lines whole — interleaved partial writes would tear the JSONL
        self._wlock = named_lock("monitor.logger", rank=66, telemetry=False)

    def bind(self, mon):
        self._mon = mon
        return self

    def _file(self):
        if self._fh is None or self._fh.closed:
            self._fh = open(self.path, "a")
        return self._fh

    def close(self):
        if self._fh is not None and not self._fh.closed:
            self._fh.close()

    def on_step(self, record: dict):
        with self._wlock:  # lock-ok: serializing the append+flush per JSONL line IS this lock's purpose (torn interleaved writes corrupt the stream); off the executor hot path
            # the sampling counter shares the lock: two threads racing
            # `_n += 1` would lose updates and skew the every-N sampling
            self._n += 1
            if self._n % self.every:
                return
            f = self._file()
            f.write(json.dumps(record, default=str) + "\n")
            f.flush()

    def write_snapshot(self, mon=None):
        mon = mon or self._mon
        if mon is None:
            from . import MONITOR

            mon = MONITOR
        line = json.dumps(json_snapshot(mon, include_steps=False),
                          default=str) + "\n"
        with self._wlock:  # lock-ok: same whole-line serialization contract as on_step; snapshots are rare control-plane writes
            f = self._file()
            f.write(line)
            f.flush()
        return self.path


# ---- the per-worker telemetry plane (ISSUE 8) -------------------------------

_TELEMETRY: Dict[str, object] = {}


def telemetry_dir() -> Optional[str]:
    """The rank-stamped telemetry directory this process was armed with
    (None outside a telemetry-armed gang)."""
    return _TELEMETRY.get("dir")


def init_worker_telemetry(telemetry_dir: Optional[str] = None,
                          rank: Optional[int] = None, mon=None,
                          every: int = 1):
    """Arm this worker's end of the gang telemetry plane.

    The gang supervisor (paddle_tpu.launch.run_gang) exports
    `PADDLE_TELEMETRY_DIR` per incarnation; each worker (via `fleet.init`,
    or an explicit call) then:

      * enables the monitor and attaches a rank-stamped
        `metrics.p<rank>.jsonl` MonitorLogger — the per-rank step/span/
        dist_event stream `tools/trace_merge.py` correlates across ranks;
      * arms the flight recorder at `BLACKBOX.p<rank>.json` (dumped on
        crash, watchdog expiry, SIGTERM drain, and injected kills);
      * chains `sys.excepthook` so an unhandled exception dumps the black
        box before the traceback prints (the "crash" trigger);
      * registers an atexit hook writing the final counter snapshot and a
        `trace.p<rank>.json` Chrome trace for the merged timeline.

    Idempotent per process; returns the attached MonitorLogger (None when
    no directory is configured — the single-process default)."""
    import atexit
    import sys

    if "logger" in _TELEMETRY:
        return _TELEMETRY["logger"]
    root = telemetry_dir or os.environ.get("PADDLE_TELEMETRY_DIR")
    if not root:
        return None
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if mon is None:
        from . import MONITOR

        mon = MONITOR
    os.makedirs(root, exist_ok=True)
    mon.enable()
    mon.set_lane(rank, f"trainer{rank}")
    mon.arm_flight_recorder(
        os.path.join(root, f"BLACKBOX.p{rank}.json"), rank)
    logger = MonitorLogger(
        os.path.join(root, f"metrics.p{rank}.jsonl"), every=every)
    logger.bind(mon)
    mon.attach_logger(logger)
    _TELEMETRY.update(dir=root, rank=rank, logger=logger)

    prev_hook = sys.excepthook

    def _crash_hook(tp, val, tb):
        mon.dump_blackbox(f"crash:{getattr(tp, '__name__', tp)}")
        prev_hook(tp, val, tb)

    sys.excepthook = _crash_hook

    def _final_flush():
        try:
            logger.write_snapshot(mon)
            export_chrome_trace(mon, os.path.join(root,
                                                  f"trace.p{rank}.json"))
        except Exception:
            pass

    atexit.register(_final_flush)
    return logger
