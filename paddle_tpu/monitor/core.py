"""Monitor core: span tracer + counter/gauge registry + step records.

Reference lineage: the C++ profiler's RecordEvent/EventList
(platform/profiler.cc) was a *profiling mode* — pay-when-on, nothing when
off, nothing queryable in between.  This subsystem is the always-available
replacement the perf rounds asked for (VERDICT r5): every layer of the
framework reports spans and counters into one process-global `Monitor`,
and exporters (exporters.py) render the same state as a Prometheus text
page, a JSON snapshot, a Chrome trace, or an appended JSONL stream.

Disabled-mode contract (the hot-path budget): `span()` is one attribute
load + branch returning a shared singleton (no allocation), `Counter.inc`
/ `Gauge.set` are one branch.  Tests pin this (tests/test_monitor.py).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

# Cap on buffered trace events / step records so an always-on monitor in a
# long-running trainer cannot grow without bound (same role as the old
# profiler's _EVENT_CAP).
EVENT_CAP = 200_000
STEP_CAP = 50_000


class _NullSpan:
    """Shared do-nothing span returned while the monitor is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **kw):
        return self


NULL_SPAN = _NullSpan()


class Span:
    """Timed region.  Nesting is tracked per-thread: depth and a tid land
    in the event buffer so the Chrome-trace exporter renders child spans
    inside their parents."""

    __slots__ = ("mon", "name", "args", "t0", "ts")

    def __init__(self, mon: "Monitor", name: str, args: Optional[dict]):
        self.mon = mon
        self.name = name
        self.args = args
        self.t0 = 0.0
        self.ts = 0.0

    def annotate(self, **kw):
        if self.args is None:
            self.args = dict(kw)
        else:
            self.args.update(kw)
        return self

    def __enter__(self):
        tls = self.mon._tls
        tls.depth = getattr(tls, "depth", 0) + 1
        self.ts = time.time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self.t0
        tls = self.mon._tls
        depth = getattr(tls, "depth", 1)
        tls.depth = depth - 1
        self.mon._record(self.name, self.ts, dur, depth - 1, self.args)
        return False


class Counter:
    """Monotonic counter.  `inc` is one branch when disabled; enabled it
    takes a per-counter lock — `value += n` alone is a LOAD/STORE pair a
    GIL switch can split, losing increments under concurrent producers."""

    __slots__ = ("mon", "name", "value", "_lock")

    def __init__(self, mon: "Monitor", name: str):
        self.mon = mon
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1):
        if self.mon.enabled:
            with self._lock:
                self.value += n
        return self


class Gauge:
    """Point-in-time value: either `set()` explicitly or `set_fn()` a
    callable evaluated lazily at read/export time (how the HBM/live-array
    gauges avoid walking `jax.live_arrays()` on the hot path)."""

    __slots__ = ("mon", "name", "value", "fn")

    def __init__(self, mon: "Monitor", name: str):
        self.mon = mon
        self.name = name
        self.value = 0.0
        self.fn: Optional[Callable[[], float]] = None

    def set(self, v: float):
        if self.mon.enabled:
            self.value = v
        return self

    def set_fn(self, fn: Callable[[], float]):
        self.fn = fn
        return self

    def read(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return float("nan")
        return float(self.value)


class Monitor:
    """Process-global telemetry sink (one instance per process; see
    monitor/__init__.py for the singleton + module-level API)."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._tls = threading.local()
        # span aggregates: name -> [calls, total_s, max_s, min_s]
        self._agg: Dict[str, list] = {}
        # raw events for trace export: (name, ts_s, dur_s, tid, depth, args)
        self._events: List[tuple] = []
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._steps: List[dict] = []
        self._loggers: List[Any] = []
        # per-device/trainer lane for merged multi-process traces
        self.lane = 0
        self.lane_name = "paddle_tpu"
        # steps/sec EMA state has its own lock: record_step also needs the
        # registry lock, and nesting the two would invite deadlock
        self._rate_lock = threading.Lock()
        self._last_step_t: Optional[float] = None
        self._steps_per_sec_ema = 0.0

    # -- lifecycle ---------------------------------------------------------
    def enable(self):
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False
        return self

    def reset(self):
        with self._lock:
            self._agg.clear()
            self._events.clear()
            self._steps.clear()
            for c in self._counters.values():
                c.value = 0
            for g in self._gauges.values():
                if g.fn is None:
                    g.value = 0.0
            self._last_step_t = None
            self._steps_per_sec_ema = 0.0
        return self

    def set_lane(self, lane: int, name: Optional[str] = None):
        """Assign this process a trace lane (pid in Chrome-trace terms) so
        merged multi-trainer traces show one lane per device/worker."""
        self.lane = int(lane)
        if name is not None:
            self.lane_name = str(name)
        return self

    # -- spans -------------------------------------------------------------
    def span(self, name: str, **args):
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, args or None)

    def observe(self, name: str, seconds: float, ts: Optional[float] = None,
                **args):
        """Record a completed duration without a context manager (the
        profiler facade's record_run, and pre-measured phases)."""
        if not self.enabled:
            return
        tls = self._tls
        self._record(name, ts if ts is not None else time.time() - seconds,
                     seconds, getattr(tls, "depth", 0), args or None)

    def _record(self, name, ts, dur, depth, args):
        tid = threading.get_ident() & 0xFFFF
        with self._lock:
            a = self._agg.get(name)
            if a is None:
                self._agg[name] = [1, dur, dur, dur]
            else:
                a[0] += 1
                a[1] += dur
                if dur > a[2]:
                    a[2] = dur
                if dur < a[3]:
                    a[3] = dur
            if len(self._events) < EVENT_CAP:
                self._events.append((name, ts, dur, tid, depth, args))

    def span_stats(self) -> Dict[str, dict]:
        with self._lock:
            return {n: {"calls": a[0], "total_s": a[1], "max_s": a[2],
                        "min_s": a[3]}
                    for n, a in self._agg.items()}

    def events(self) -> List[tuple]:
        with self._lock:
            return list(self._events)

    # -- counters / gauges -------------------------------------------------
    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(self, name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(self, name))
        return g

    def counter_values(self) -> Dict[str, int]:
        return {n: c.value for n, c in sorted(self._counters.items())}

    def gauge_values(self) -> Dict[str, float]:
        return {n: g.read() for n, g in sorted(self._gauges.items())}

    # -- step records ------------------------------------------------------
    def record_step(self, record: dict):
        """Append one per-`run()` record (executor step breakdown) and fan
        it out to attached loggers.  Only `kind="step"` records (the
        executor's own) advance the executor.steps counter and steps/sec
        EMA — auxiliary kinds (pipeline_step, ...) describe the SAME
        training step from another layer and must not double-count it."""
        if not self.enabled:
            return
        record = dict(record)
        record.setdefault("kind", "step")
        record.setdefault("ts", time.time())
        is_exec_step = record["kind"] == "step"
        steps_counter = self.counter("executor.steps")  # before _lock: counter() locks too
        if is_exec_step:
            rate_gauge = self.gauge("executor.steps_per_sec_ema")
            now = time.perf_counter()
            with self._rate_lock:
                if self._last_step_t is not None:
                    dt = now - self._last_step_t
                    if dt > 0:
                        inst = 1.0 / dt
                        ema = self._steps_per_sec_ema
                        self._steps_per_sec_ema = inst if ema == 0.0 else 0.9 * ema + 0.1 * inst
                        rate_gauge.set(self._steps_per_sec_ema)
                self._last_step_t = now
        record["step"] = steps_counter.value
        with self._lock:
            if len(self._steps) < STEP_CAP:
                self._steps.append(record)
        if is_exec_step:
            steps_counter.inc()
        for lg in list(self._loggers):
            try:
                lg.on_step(record)
            except Exception:
                pass

    def step_records(self) -> List[dict]:
        with self._lock:
            return list(self._steps)

    # -- loggers -----------------------------------------------------------
    def attach_logger(self, logger):
        self._loggers.append(logger)
        return logger

    def detach_logger(self, logger):
        if logger in self._loggers:
            self._loggers.remove(logger)
        close = getattr(logger, "close", None)
        if callable(close):
            close()
