"""Monitor core: span tracer + counter/gauge registry + step records.

Reference lineage: the C++ profiler's RecordEvent/EventList
(platform/profiler.cc) was a *profiling mode* — pay-when-on, nothing when
off, nothing queryable in between.  This subsystem is the always-available
replacement the perf rounds asked for (VERDICT r5): every layer of the
framework reports spans and counters into one process-global `Monitor`,
and exporters (exporters.py) render the same state as a Prometheus text
page, a JSON snapshot, a Chrome trace, or an appended JSONL stream.

Disabled-mode contract (the hot-path budget): `span()` is one attribute
load + branch returning a shared singleton (no allocation), `Counter.inc`
/ `Gauge.set` are one branch.  Tests pin this (tests/test_monitor.py).

Flight recorder (ISSUE 8): alongside the capped buffers, the monitor
keeps a small bounded ring of the most RECENT step records and span
events.  `arm_flight_recorder(path, rank)` names a `BLACKBOX.p<rank>.json`
destination; `dump_blackbox(reason)` writes the ring plus the live
counter/gauge state there atomically (tmp + fsync + rename, so a SIGKILL
half-write can never pass for a black box).  The first dump wins — a
watchdog expiry that cascades into a crash keeps the watchdog's
attribution.  Ring appends ride the locks the buffers already take, so
the always-on recorder adds two deque appends to the hot path
(tests/test_telemetry_plane.py bounds the cost).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..core.locks import named_lock

# Cap on buffered trace events / step records so an always-on monitor in a
# long-running trainer cannot grow without bound (same role as the old
# profiler's _EVENT_CAP).
EVENT_CAP = 200_000
STEP_CAP = 50_000
# Flight-recorder ring depth: the "last N steps before it died" a crash
# black box carries (per record class: step records and span events).
FLIGHT_RECORDER_CAP = 256
# Request-flight trace ring (ISSUE 16): the newest N closed per-request
# span trees (serving/tracing.py) kept live for the Chrome-trace request
# lanes and `tools/serve_trace.py` — same bounded-ring discipline as the
# flight recorder, appends riding the registry lock.
TRACE_RING_CAP = 1024
# Slow/bad-request exemplar ring: full traces of deadline misses, sheds,
# and errors, retained past the trace ring's churn so a post-mortem black
# box still carries the episodes that actually burned the SLO.
EXEMPLAR_CAP = 64


class _NullSpan:
    """Shared do-nothing span returned while the monitor is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **kw):
        return self


NULL_SPAN = _NullSpan()


class Span:
    """Timed region.  Nesting is tracked per-thread: depth and a tid land
    in the event buffer so the Chrome-trace exporter renders child spans
    inside their parents."""

    __slots__ = ("mon", "name", "args", "t0", "ts")

    def __init__(self, mon: "Monitor", name: str, args: Optional[dict]):
        self.mon = mon
        self.name = name
        self.args = args
        self.t0 = 0.0
        self.ts = 0.0

    def annotate(self, **kw):
        if self.args is None:
            self.args = dict(kw)
        else:
            self.args.update(kw)
        return self

    def __enter__(self):
        tls = self.mon._tls
        tls.depth = getattr(tls, "depth", 0) + 1
        self.ts = time.time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self.t0
        tls = self.mon._tls
        depth = getattr(tls, "depth", 1)
        tls.depth = depth - 1
        self.mon._record(self.name, self.ts, dur, depth - 1, self.args)
        return False


class Counter:
    """Monotonic counter.  `inc` is one branch when disabled; enabled it
    takes a per-counter lock — `value += n` alone is a LOAD/STORE pair a
    GIL switch can split, losing increments under concurrent producers."""

    __slots__ = ("mon", "name", "value", "_lock")

    def __init__(self, mon: "Monitor", name: str):
        self.mon = mon
        self.name = name
        self.value = 0
        # telemetry=False on every monitor-internal lock: lock telemetry
        # records through Counter.inc, so instrumenting the lock inc
        # itself takes would recurse/deadlock
        self._lock = named_lock("monitor.counter", rank=68, telemetry=False)

    def inc(self, n: int = 1):
        if self.mon.enabled:
            with self._lock:
                self.value += n
        return self


class Gauge:
    """Point-in-time value: either `set()` explicitly or `set_fn()` a
    callable evaluated lazily at read/export time (how the HBM/live-array
    gauges avoid walking `jax.live_arrays()` on the hot path)."""

    __slots__ = ("mon", "name", "value", "fn")

    def __init__(self, mon: "Monitor", name: str):
        self.mon = mon
        self.name = name
        self.value = 0.0
        self.fn: Optional[Callable[[], float]] = None

    def set(self, v: float):
        if self.mon.enabled:
            self.value = v
        return self

    def set_fn(self, fn: Callable[[], float]):
        self.fn = fn
        return self

    def read(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return float("nan")
        return float(self.value)


class Monitor:
    """Process-global telemetry sink (one instance per process; see
    monitor/__init__.py for the singleton + module-level API)."""

    def __init__(self):
        self.enabled = False
        self._lock = named_lock("monitor.registry", rank=64, telemetry=False)
        self._tls = threading.local()
        # span aggregates: name -> [calls, total_s, max_s, min_s]
        self._agg: Dict[str, list] = {}
        # raw events for trace export: (name, ts_s, dur_s, tid, depth, args)
        self._events: List[tuple] = []
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._steps: List[dict] = []
        self._loggers: List[Any] = []
        # flight recorder: bounded rings of the NEWEST records (the capped
        # buffers above keep the oldest), dumped as a black box on crash
        self._bb_steps: deque = deque(maxlen=FLIGHT_RECORDER_CAP)
        self._bb_events: deque = deque(maxlen=FLIGHT_RECORDER_CAP)
        # request-flight traces (ISSUE 16): newest-N closed span trees,
        # plus the slow/bad exemplars the black box keeps past ring churn
        self._traces: deque = deque(maxlen=TRACE_RING_CAP)
        self._exemplars: deque = deque(maxlen=EXEMPLAR_CAP)
        self._bb_path: Optional[str] = None
        self._bb_rank = 0
        self._bb_dumped: Optional[str] = None
        # dump latch lock — NOT self._lock: blackbox_snapshot takes that
        # one, and the latch must stay held across snapshot + write
        self._bb_dump_lock = named_lock("monitor.blackbox", rank=60,
                                        telemetry=False)
        # per-device/trainer lane for merged multi-process traces
        self.lane = 0
        self.lane_name = "paddle_tpu"
        # steps/sec EMA state has its own lock: record_step also needs the
        # registry lock, and nesting the two would invite deadlock
        self._rate_lock = named_lock("monitor.rate", rank=62, telemetry=False)
        self._last_step_t: Optional[float] = None
        self._steps_per_sec_ema = 0.0

    # -- lifecycle ---------------------------------------------------------
    def enable(self):
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False
        return self

    def reset(self):
        with self._lock:
            self._agg.clear()
            self._events.clear()
            self._steps.clear()
            self._bb_steps.clear()
            self._bb_events.clear()
            self._traces.clear()
            self._exemplars.clear()
            # a reset starts a fresh run: the one-shot dump latch re-opens
            # (the armed path survives — re-arm to change it)
            self._bb_dumped = None
            for c in self._counters.values():
                c.value = 0
            for g in self._gauges.values():
                if g.fn is None:
                    g.value = 0.0
            self._last_step_t = None
            self._steps_per_sec_ema = 0.0
        return self

    def set_lane(self, lane: int, name: Optional[str] = None):
        """Assign this process a trace lane (pid in Chrome-trace terms) so
        merged multi-trainer traces show one lane per device/worker."""
        self.lane = int(lane)
        if name is not None:
            self.lane_name = str(name)
        return self

    # -- spans -------------------------------------------------------------
    def span(self, name: str, **args):
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, args or None)

    def observe(self, name: str, seconds: float, ts: Optional[float] = None,
                **args):
        """Record a completed duration without a context manager (the
        profiler facade's record_run, and pre-measured phases)."""
        if not self.enabled:
            return
        tls = self._tls
        self._record(name, ts if ts is not None else time.time() - seconds,
                     seconds, getattr(tls, "depth", 0), args or None)

    def _record(self, name, ts, dur, depth, args):
        tid = threading.get_ident() & 0xFFFF
        with self._lock:
            a = self._agg.get(name)
            if a is None:
                self._agg[name] = [1, dur, dur, dur]
            else:
                a[0] += 1
                a[1] += dur
                if dur > a[2]:
                    a[2] = dur
                if dur < a[3]:
                    a[3] = dur
            if len(self._events) < EVENT_CAP:
                self._events.append((name, ts, dur, tid, depth, args))
            self._bb_events.append((name, ts, dur, tid, depth, args))

    def span_stats(self) -> Dict[str, dict]:
        with self._lock:
            return {n: {"calls": a[0], "total_s": a[1], "max_s": a[2],
                        "min_s": a[3]}
                    for n, a in self._agg.items()}

    def events(self) -> List[tuple]:
        with self._lock:
            return list(self._events)

    # -- counters / gauges -------------------------------------------------
    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(self, name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(self, name))
        return g

    def counter_values(self) -> Dict[str, int]:
        return {n: c.value for n, c in sorted(self._counters.items())}

    def gauge_values(self) -> Dict[str, float]:
        return {n: g.read() for n, g in sorted(self._gauges.items())}

    # -- step records ------------------------------------------------------
    def record_step(self, record: dict):
        """Append one per-`run()` record (executor step breakdown) and fan
        it out to attached loggers.  Only `kind="step"` records (the
        executor's own) advance the executor.steps counter and steps/sec
        EMA — auxiliary kinds (pipeline_step, ...) describe the SAME
        training step from another layer and must not double-count it."""
        if not self.enabled:
            return
        record = dict(record)
        record.setdefault("kind", "step")
        record.setdefault("ts", time.time())
        is_exec_step = record["kind"] == "step"
        steps_counter = self.counter("executor.steps")  # before _lock: counter() locks too
        if is_exec_step:
            rate_gauge = self.gauge("executor.steps_per_sec_ema")
            now = time.perf_counter()
            with self._rate_lock:
                if self._last_step_t is not None:
                    dt = now - self._last_step_t
                    if dt > 0:
                        inst = 1.0 / dt
                        ema = self._steps_per_sec_ema
                        self._steps_per_sec_ema = inst if ema == 0.0 else 0.9 * ema + 0.1 * inst
                        rate_gauge.set(self._steps_per_sec_ema)
                self._last_step_t = now
        record.setdefault("lane", self.lane)
        record["step"] = steps_counter.value
        with self._lock:
            if len(self._steps) < STEP_CAP:
                self._steps.append(record)
            self._bb_steps.append(record)
        if is_exec_step:
            steps_counter.inc()
        for lg in list(self._loggers):
            try:
                lg.on_step(record)
            except Exception:
                pass

    def step_records(self) -> List[dict]:
        with self._lock:
            return list(self._steps)

    # -- request-flight traces (ISSUE 16) ----------------------------------
    def record_trace(self, record: dict):
        """Append one CLOSED per-request span tree (a `serving_trace`
        record from serving/tracing.py) to the bounded trace ring, and
        fan it through `record_step` so it rides the JSONL stream, the
        step buffer, and the flight-recorder ring like every other
        record kind.  One branch when disabled."""
        if not self.enabled:
            return
        record = dict(record)
        record.setdefault("kind", "serving_trace")
        with self._lock:
            self._traces.append(record)
        self.record_step(record)

    def request_traces(self) -> List[dict]:
        """The newest TRACE_RING_CAP closed request traces (exporters
        render them as Chrome-trace request lanes)."""
        with self._lock:
            return list(self._traces)

    def record_exemplar(self, record: dict):
        """Retain a slow/bad-request trace (deadline miss, shed, error,
        rejected publish) in the exemplar ring the black box carries —
        these must survive the trace ring's churn so a post-mortem still
        shows the episodes that burned the SLO."""
        if not self.enabled:
            return
        with self._lock:
            self._exemplars.append(dict(record))

    def exemplars(self) -> List[dict]:
        with self._lock:
            return list(self._exemplars)

    # -- flight recorder ---------------------------------------------------
    def arm_flight_recorder(self, path: str, rank: int = 0) -> "Monitor":
        """Name the black-box destination (`BLACKBOX.p<rank>.json` under a
        gang's telemetry dir).  Arming does not enable the monitor — the
        telemetry plane (exporters.init_worker_telemetry) does both."""
        self._bb_path = str(path)
        self._bb_rank = int(rank)
        return self

    def flight_recorder_path(self) -> Optional[str]:
        return self._bb_path

    def blackbox_snapshot(self, reason: str = "manual") -> dict:
        """The flight-recorder ring rendered as one JSON-able document:
        the last FLIGHT_RECORDER_CAP step records and span events plus the
        live counter/gauge state — what the gang was doing right before it
        died."""
        with self._lock:
            steps = list(self._bb_steps)
            exemplars = list(self._exemplars)
            events = [
                {"name": n, "ts": ts, "dur_s": dur, "tid": tid,
                 "depth": depth,
                 "args": ({k: str(v) for k, v in args.items()}
                          if args else None)}
                for (n, ts, dur, tid, depth, args) in self._bb_events
            ]
        try:
            gauges = self.gauge_values()
        except Exception:
            gauges = {}
        return {"kind": "blackbox", "reason": str(reason),
                "rank": self._bb_rank, "pid": os.getpid(),
                "ts": time.time(), "lane": self.lane,
                "lane_name": self.lane_name, "steps": steps,
                "events": events, "exemplars": exemplars,
                "counters": self.counter_values(),
                "gauges": gauges}

    def dump_blackbox(self, reason: str = "manual",
                      path: Optional[str] = None) -> Optional[str]:
        """Write the black box atomically (tmp + fsync + rename) and return
        its path; no-op (None) when unarmed.  The FIRST dump wins: a
        watchdog expiry that cascades into a crash/exit keeps the
        watchdog's attribution instead of being overwritten by the
        secondary failure.  The latch is lock-held across snapshot+write:
        a watchdog-thread dump racing a crash-hook dump must not both
        pass the check and overwrite each other.  Never raises — this
        runs on crash paths."""
        with self._bb_dump_lock:  # lock-ok: one-shot crash latch — the first-dump-wins guarantee REQUIRES holding it across snapshot+write; contention only exists while the process is already dying
            if self._bb_dumped is not None:
                return self._bb_dumped
            p = path or self._bb_path
            if p is None:
                return None
            try:
                snap = self.blackbox_snapshot(reason)
                tmp = f"{p}.tmp{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(snap, f, default=str)
                    f.flush()
                    os.fsync(f.fileno())  # to disk before a SIGKILL lands
                os.replace(tmp, p)
                self._bb_dumped = p
                return p
            except Exception:
                return None

    # -- loggers -----------------------------------------------------------
    def attach_logger(self, logger):
        self._loggers.append(logger)
        return logger

    def detach_logger(self, logger):
        if logger in self._loggers:
            self._loggers.remove(logger)
        close = getattr(logger, "close", None)
        if callable(close):
            close()
