"""paddle_tpu.monitor — the framework-wide observability subsystem.

Every layer reports into one process-global `Monitor`:

    from paddle_tpu import monitor

    monitor.enable()
    with monitor.span("compile", program=uuid):      # nested, thread-safe
        ...
    monitor.counter("executor.cache_miss").inc()
    monitor.gauge("reader.queue_depth").set(3)

    print(monitor.export_prometheus())               # text exposition
    monitor.export_json("snapshot.json")             # perf_report input
    monitor.export_chrome_trace("trace.json")        # chrome://tracing
    log = monitor.attach_logger(monitor.MonitorLogger("metrics.jsonl"))

Disabled (the default) every entry point is a branch: `span()` returns a
shared null singleton, `inc`/`set` are no-ops.  `paddle_tpu.profiler` is a
compatibility facade over this module.

Instrumented out of the box: `core/executor.py` (per-run step breakdown —
lowering / compile / execute / fetch spans, cache-hit + recompile
counters, steps/sec EMA), `core/lowering.py` (per-op lower counts),
`reader.py` (queue depth / wait), `fleet.py` + `dygraph/parallel.py`
(worker lanes, collective bytes), memstats gauges (live HBM bytes).
See docs/observability.md.
"""
from __future__ import annotations

from .core import Counter, Gauge, Monitor, NULL_SPAN, Span  # noqa: F401
from . import exporters as _exp
from .exporters import MonitorLogger, prometheus_text, summary_table  # noqa: F401
from .memstats import register_memory_gauges

MONITOR = Monitor()
register_memory_gauges(MONITOR)


def get_monitor() -> Monitor:
    return MONITOR


def enable():
    return MONITOR.enable()


def disable():
    return MONITOR.disable()


def is_enabled() -> bool:
    return MONITOR.enabled


def reset():
    return MONITOR.reset()


def span(name: str, **args):
    return MONITOR.span(name, **args)


def observe(name: str, seconds: float, **args):
    return MONITOR.observe(name, seconds, **args)


def counter(name: str) -> Counter:
    return MONITOR.counter(name)


def gauge(name: str) -> Gauge:
    return MONITOR.gauge(name)


def record_step(record: dict):
    return MONITOR.record_step(record)


def step_records():
    return MONITOR.step_records()


def set_lane(lane: int, name=None):
    return MONITOR.set_lane(lane, name)


def attach_logger(logger):
    if isinstance(logger, MonitorLogger):
        logger.bind(MONITOR)
    return MONITOR.attach_logger(logger)


def detach_logger(logger):
    return MONITOR.detach_logger(logger)


def export_prometheus() -> str:
    return prometheus_text(MONITOR)


def export_json(path: str, include_steps: bool = True) -> str:
    return _exp.export_json(MONITOR, path, include_steps)


def json_snapshot(include_steps: bool = True) -> dict:
    return _exp.json_snapshot(MONITOR, include_steps)


def export_chrome_trace(path: str, pid=None, process_name=None) -> int:
    return _exp.export_chrome_trace(MONITOR, pid=pid, path=path,
                                    process_name=process_name)


def merge_chrome_traces(named_paths, out_path: str) -> str:
    return _exp.merge_chrome_traces(named_paths, out_path)


def summary(sorted_key: str = "total") -> str:
    return summary_table(MONITOR, sorted_key)
