"""paddle_tpu.monitor — the framework-wide observability subsystem.

Every layer reports into one process-global `Monitor`:

    from paddle_tpu import monitor

    monitor.enable()
    with monitor.span("compile", program=uuid):      # nested, thread-safe
        ...
    monitor.counter("executor.cache_miss").inc()
    monitor.gauge("reader.queue_depth").set(3)

    print(monitor.export_prometheus())               # text exposition
    monitor.export_json("snapshot.json")             # perf_report input
    monitor.export_chrome_trace("trace.json")        # chrome://tracing
    log = monitor.attach_logger(monitor.MonitorLogger("metrics.jsonl"))

Disabled (the default) every entry point is a branch: `span()` returns a
shared null singleton, `inc`/`set` are no-ops.  `paddle_tpu.profiler` is a
compatibility facade over this module.

Instrumented out of the box: `core/executor.py` (per-run step breakdown —
lowering / compile / execute / fetch spans, cache-hit + recompile
counters, steps/sec EMA), `core/lowering.py` (per-op lower counts),
`reader.py` (queue depth / wait), `fleet.py` + `dygraph/parallel.py`
(worker lanes, collective bytes), memstats gauges (live HBM bytes).
See docs/observability.md.
"""
from __future__ import annotations

import time

from .core import (Counter, EXEMPLAR_CAP, FLIGHT_RECORDER_CAP,  # noqa: F401
                   Gauge, Monitor, NULL_SPAN, Span, TRACE_RING_CAP)
from . import exporters as _exp
from .exporters import (MonitorLogger, escape_label_value,  # noqa: F401
                        prometheus_text, summary_table)
from .memstats import register_memory_gauges

__all__ = [
    "Counter", "Gauge", "Monitor", "MonitorLogger", "Span", "NULL_SPAN",
    "FLIGHT_RECORDER_CAP", "TRACE_RING_CAP", "EXEMPLAR_CAP", "MONITOR",
    "get_monitor", "enable", "disable",
    "is_enabled", "reset", "span", "observe", "counter", "gauge",
    "record_step", "step_records", "record_trace", "record_fleet_event",
    "request_traces",
    "record_exemplar", "exemplars", "set_lane", "attach_logger",
    "detach_logger", "export_prometheus", "export_json", "json_snapshot",
    "export_chrome_trace", "merge_chrome_traces", "summary",
    "prometheus_text", "escape_label_value", "arm_flight_recorder",
    "dump_blackbox", "blackbox_snapshot", "init_worker_telemetry",
    "telemetry_dir", "register_memory_gauges",
]

MONITOR = Monitor()
register_memory_gauges(MONITOR)


def get_monitor() -> Monitor:
    return MONITOR


def enable():
    return MONITOR.enable()


def disable():
    return MONITOR.disable()


def is_enabled() -> bool:
    return MONITOR.enabled


def reset():
    return MONITOR.reset()


def span(name: str, **args):
    return MONITOR.span(name, **args)


def observe(name: str, seconds: float, **args):
    return MONITOR.observe(name, seconds, **args)


def counter(name: str) -> Counter:
    return MONITOR.counter(name)


def gauge(name: str) -> Gauge:
    return MONITOR.gauge(name)


def record_step(record: dict):
    return MONITOR.record_step(record)


def step_records():
    return MONITOR.step_records()


def record_trace(record: dict):
    """Append a closed per-request span tree (serving/tracing.py) to the
    bounded trace ring + the step/JSONL streams (ISSUE 16)."""
    return MONITOR.record_trace(record)


def record_fleet_event(action: str, **fields):
    """One serving-fleet lifecycle transition (replica_dead /
    replica_restarted / roll_started / roll_halted / roll_converged /
    ...) as a `kind="fleet_event"` step record plus a per-action
    counter — the stream `serve_trace --fleet` renders as roll episodes
    and `perf_report --check` gates for roll convergence (ISSUE 18)."""
    rec = {"kind": "fleet_event", "action": action, "ts": time.time(),
           **fields}
    MONITOR.counter(f"serving.fleet.events[{action}]").inc()
    MONITOR.record_step(rec)
    return rec


def request_traces():
    return MONITOR.request_traces()


def record_exemplar(record: dict):
    """Retain a slow/bad-request trace in the black box's exemplar ring."""
    return MONITOR.record_exemplar(record)


def exemplars():
    return MONITOR.exemplars()


def set_lane(lane: int, name=None):
    return MONITOR.set_lane(lane, name)


def attach_logger(logger):
    if isinstance(logger, MonitorLogger):
        logger.bind(MONITOR)
    return MONITOR.attach_logger(logger)


def detach_logger(logger):
    return MONITOR.detach_logger(logger)


def arm_flight_recorder(path: str, rank: int = 0) -> Monitor:
    """Name this process's black-box file (`BLACKBOX.p<rank>.json`); the
    bounded last-N ring of steps/spans is dumped there on crash, watchdog
    expiry, SIGTERM drain, and injected kills."""
    return MONITOR.arm_flight_recorder(path, rank)


def dump_blackbox(reason: str = "manual", path=None):
    """Atomically write the flight-recorder black box (first dump wins);
    returns its path, or None when unarmed."""
    return MONITOR.dump_blackbox(reason, path)


def blackbox_snapshot(reason: str = "manual") -> dict:
    return MONITOR.blackbox_snapshot(reason)


def init_worker_telemetry(telemetry_dir=None, rank=None, every: int = 1):
    """Arm this worker's end of the gang telemetry plane (rank-stamped
    JSONL stream + flight recorder + crash hook + exit-time Chrome trace);
    no-op outside a telemetry-armed gang.  See exporters.py."""
    return _exp.init_worker_telemetry(telemetry_dir, rank, MONITOR, every)


def telemetry_dir():
    return _exp.telemetry_dir()


def export_prometheus(labels=None) -> str:
    return prometheus_text(MONITOR, labels=labels)


def export_json(path: str, include_steps: bool = True) -> str:
    return _exp.export_json(MONITOR, path, include_steps)


def json_snapshot(include_steps: bool = True) -> dict:
    return _exp.json_snapshot(MONITOR, include_steps)


def export_chrome_trace(path: str, pid=None, process_name=None) -> int:
    return _exp.export_chrome_trace(MONITOR, pid=pid, path=path,
                                    process_name=process_name)


def merge_chrome_traces(named_paths, out_path: str) -> str:
    return _exp.merge_chrome_traces(named_paths, out_path)


def summary(sorted_key: str = "total") -> str:
    return summary_table(MONITOR, sorted_key)
