"""Scope: name -> device array store (reference: framework/scope.h:45).

The reference's Scope maps names to Variables holding LoDTensors on some
Place; kernels mutate them in place.  Here the executor is functional — a
compiled step returns new arrays — and the Scope is just the persistent
name->jax.Array dictionary those results are written back to between runs.
Hierarchy (kid scopes) is kept for API parity with `Scope::NewScope`.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

RNG_STATE_VAR = "__rng_state__"


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        import uuid

        self._uuid = uuid.uuid4().hex
        self._vars: Dict[str, object] = {}
        self.parent = parent
        self.kids: List["Scope"] = []

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self.kids.append(kid)
        return kid

    def set_var(self, name: str, value) -> None:
        self._vars[name] = value

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        return self.find_var(name) is not None

    def erase(self, names) -> None:
        for n in names:
            self._vars.pop(n, None)

    def local_var_names(self) -> List[str]:
        return list(self._vars)

    def var_names(self) -> List[str]:
        names = set()
        s: Optional[Scope] = self
        while s is not None:
            names.update(s._vars)
            s = s.parent
        return sorted(names)

    def to_numpy(self, name: str) -> np.ndarray:
        v = self.find_var(name)
        if v is None:
            raise KeyError(f"variable {name!r} not in scope")
        return np.asarray(v)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope):
    """reference executor.py scope_guard: swap the global scope."""
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old
