"""Static analysis over the Program IR: verifier, shape/dtype inference,
hazard lints — everything that can be checked BEFORE lowering.

Reference counterparts: `framework/ir/` pass infrastructure plus the
compile-time `InferShape` contract (`framework/shape_inference.h`): every op
validates its inputs and declares its outputs' shapes/dtypes before any
kernel runs.  The TPU rebuild long had only the hook (`core/registry.py`
`InferFn` / `infer_and_check`); this module supplies the machinery and the
diagnostics vocabulary:

  * **Structural verifier** (`verify_structure`): def-before-use per block,
    dangling var references, ops with no registered lowering, orphan
    sub-block attrs, duplicate writes to parameters.  Feed/fetch target
    existence rides along when the caller knows them (`verify_feed_fetch`).
  * **Shape/dtype inference** (`InferContext` + rule factories): per-op
    `infer=` functions registered next to the lowerings (ops/*) run at
    `Block.append_op` time via `registry.infer_and_check`, unify `-1`
    (dynamic) dims against declared shapes, and raise classified
    `ShapeInferenceError`s naming the op, var, and block instead of letting
    a malformed program die deep inside JAX tracing.
  * **Hazard lints**: donation/aliasing (in-place persistable state read
    again later in the step), recompile hazards (feed vars with dynamic
    non-batch dims — every distinct shape is a fresh XLA compile),
    collective order (collectives under divergent control flow, or rank
    programs issuing collectives in different static orders), and RNG
    determinism (unseeded programs consuming randomness).

Entry points: `verify_program` (diagnostics list), `check_program` (raises
on error-severity diagnostics).  `core/passes.py` verifies after every pass
and the executor verifies on each compile-cache miss, both gated by
`FLAGS_verify_program` (off|structural|full).  `tools/program_lint.py` is
the CLI over the same machinery.  Monitor surface: `analysis.verify_runs`,
`analysis.diag.<code>` counters, `analysis.infer_coverage_frac` gauge.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..errors import FatalError
from ..monitor import MONITOR as _MON
from . import registry
from .dtypes import canonical_dtype
from .program import Block, Operator, Parameter, Program

__all__ = [
    # diagnostics + errors
    "Diagnostic", "StaticAnalysisError", "ProgramVerificationError",
    "ShapeInferenceError", "PassVerificationError",
    "SEV_ERROR", "SEV_WARNING", "LEVELS",
    # shape algebra
    "unify_dim", "unify_shape", "broadcast_dim", "fluid_broadcast",
    # inference engine
    "InferContext", "as_infer", "register_rule", "register_unary_infer",
    "register_elementwise_infer", "register_reduce_infer",
    "register_state_update_infer", "infer_coverage",
    # verifier + lints
    "verify_structure", "verify_feed_fetch", "verify_shapes",
    "lint_donation", "lint_recompile", "lint_determinism",
    "lint_collective_order", "collective_signature",
    # entry points
    "verify_program", "check_program",
    # shared op vocabularies
    "BOOL_OUT_OPS", "RNG_OPS", "COLLECTIVE_OPS", "STRUCTURAL_OPS",
]

# Ops the executor handles itself; they have no lowering and no infer fn.
STRUCTURAL_OPS = ("feed", "fetch", "backward")

# Sub-block owners with loop semantics: body reads of body-written vars are
# loop carries (previous iteration's value), not use-before-def.
_LOOP_OPS = ("while", "dynamic_rnn")

# Compare/logical ops produce bool whatever the operand dtype.  Shared by
# the infer registrations (ops/*) and the layer builders (math_sugar) so
# the two cannot drift.
BOOL_OUT_OPS = frozenset({
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_xor",
})

# RNG-consuming op types and how an op can pin its own stream.
RNG_OPS = frozenset({
    "dropout", "uniform_random", "gaussian_random",
    "truncated_gaussian_random", "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like", "sampling_id", "random_crop",
})

# Program-level ops whose lowering issues collectives, and the attr naming
# the mesh axis they communicate over.  (GSPMD-inserted collectives — dp
# gradient all-reduces etc. — are derived deterministically from sharding
# and need no ordering lint.)
COLLECTIVE_OPS = {"pipeline": "axis_name", "ring_attention": "sp_axis"}

SEV_ERROR = "error"
SEV_WARNING = "warning"

DYN = -1  # the dynamic-dim sentinel in declared shapes


# --------------------------------------------------------------------------
# diagnostics
# --------------------------------------------------------------------------

@dataclass
class Diagnostic:
    """One finding, with enough provenance to locate the offending op."""

    code: str                 # e.g. "use_before_def", "donation_hazard"
    severity: str             # SEV_ERROR | SEV_WARNING
    message: str
    block: int = 0
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None

    def __str__(self):
        where = f"block {self.block}"
        if self.op_idx is not None:
            where += f" op #{self.op_idx}"
        if self.op_type is not None:
            where += f" ({self.op_type})"
        tail = f" [var {self.var!r}]" if self.var else ""
        return f"[{self.severity}:{self.code}] {where}: {self.message}{tail}"


class StaticAnalysisError(FatalError):
    """Base of build-time analysis failures (never retried: the program
    itself is wrong, not the run)."""

    def __init__(self, message: str, diagnostics: Optional[List[Diagnostic]] = None):
        super().__init__(message, phase="build")
        self.diagnostics = list(diagnostics or [])


class ProgramVerificationError(StaticAnalysisError):
    """verify/check found error-severity diagnostics."""


class ShapeInferenceError(StaticAnalysisError):
    """An op's declared shapes/dtypes are inconsistent with its inputs
    (raised at `append_op` time via `registry.infer_and_check`)."""


class PassVerificationError(ProgramVerificationError):
    """A program-rewrite pass left the program verifier-dirty."""

    def __init__(self, pass_name: str, diagnostics: List[Diagnostic]):
        lines = "\n".join(f"  {d}" for d in diagnostics)
        super().__init__(
            f"pass {pass_name!r} broke the program "
            f"(FLAGS_verify_program caught it before lowering):\n{lines}",
            diagnostics,
        )
        self.pass_name = pass_name


def _op_index(block: Block, op: Operator) -> Optional[int]:
    """Index of `op` in its block; O(1) for the append_op hot path."""
    if block.ops and block.ops[-1] is op:
        return len(block.ops) - 1
    try:
        return block.ops.index(op)
    except ValueError:
        return None


# --------------------------------------------------------------------------
# shape algebra: -1-aware unification / broadcasting
# --------------------------------------------------------------------------

def unify_dim(a: int, b: int) -> Optional[int]:
    """Unify two dims where -1 is unknown; None on conflict."""
    if a == b:
        return a
    if a == DYN:
        return b
    if b == DYN:
        return a
    return None


def unify_shape(a: Sequence[int], b: Sequence[int]) -> Optional[Tuple[int, ...]]:
    """Elementwise dim unification; None on rank or dim conflict."""
    if len(a) != len(b):
        return None
    out = []
    for da, db in zip(a, b):
        d = unify_dim(int(da), int(db))
        if d is None:
            return None
        out.append(d)
    return tuple(out)


def broadcast_dim(a: int, b: int) -> Optional[int]:
    """Numpy-style broadcast of two dims, -1-aware; None on conflict.

    -1 vs d>1 resolves to d (a runtime value of either 1 or d broadcasts to
    d; anything else errors at runtime too).  -1 vs 1 stays -1.
    """
    if a == b:
        return a
    if a == 1:
        return b
    if b == 1:
        return a
    if a == DYN:
        return b if b != 1 else DYN
    if b == DYN:
        return a if a != 1 else DYN
    return None


def fluid_broadcast(x: Sequence[int], y: Sequence[int], axis: int = -1
                    ) -> Optional[Tuple[int, ...]]:
    """Fluid elementwise broadcasting: Y aligns to X starting at `axis`
    (axis=-1: trailing/numpy alignment).  Returns the out shape or None on
    a dim conflict."""
    x = [int(d) for d in x]
    y = [int(d) for d in y]
    if len(y) > len(x):
        x, y = y, x  # rare mirrored case (scalar-first sugar)
        axis = -1
    if axis == -1 or len(x) == len(y):
        pad = len(x) - len(y)
        y_full = [1] * pad + y
    else:
        pad_right = len(x) - axis - len(y)
        if pad_right < 0:
            return None
        y_full = [1] * axis + y + [1] * pad_right
    out = []
    for dx, dy in zip(x, y_full):
        d = broadcast_dim(dx, dy)
        if d is None:
            return None
        out.append(d)
    return tuple(out)


def _scalarish(shape) -> bool:
    """() and (1,) both mean 'scalar' across the op vocabulary."""
    return len(shape) <= 1 and all(d == 1 for d in shape)


def _dtype_kind(name: str) -> str:
    """'f' (any float incl. bfloat16), 'i'/'u' (ints), 'b' (bool)."""
    if name in ("bfloat16", "float16", "float32", "float64"):
        return "f"
    if name == "bool":
        return "b"
    if name.startswith("uint"):
        return "u"
    if name.startswith("int"):
        return "i"
    return "?"


# --------------------------------------------------------------------------
# shape/dtype inference engine
# --------------------------------------------------------------------------

# When set, infer rules only CHECK: `InferContext.set_out` raises on
# conflicts but never fills/narrows declared shapes (whole-program
# re-verification must not mutate the program it verifies).
_READONLY = False


class InferContext:
    """Helper handed to per-op infer rules: slot-level shape/dtype access
    plus declared-vs-inferred unification with full provenance on failure."""

    def __init__(self, op: Operator, block: Block):
        self.op = op
        self.block = block

    # -- inputs ----------------------------------------------------------
    def in_var(self, slot: str, i: int = 0):
        names = self.op.input(slot)
        if i >= len(names):
            return None
        return self.block._find_var_recursive(names[i])

    def in_shape(self, slot: str, i: int = 0) -> Optional[Tuple[int, ...]]:
        v = self.in_var(slot, i)
        if v is None or v.shape is None:
            return None
        return tuple(v.shape)

    def in_dtype(self, slot: str, i: int = 0) -> Optional[str]:
        v = self.in_var(slot, i)
        return None if v is None else v.dtype

    def n_inputs(self, slot: str) -> int:
        return len(self.op.input(slot))

    # -- failure with provenance ----------------------------------------
    def fail(self, message: str, var: Optional[str] = None):
        idx = _op_index(self.block, self.op)
        raise ShapeInferenceError(
            f"shape/dtype inference failed for op #{idx} "
            f"({self.op.type!r}) in block {self.block.idx}: {message}"
            + (f" [var {var!r}]" if var else "")
        )

    # -- outputs ---------------------------------------------------------
    def set_out(self, slot: str, shape, dtype=None, i: int = 0):
        """Declare/validate one output: unify the inferred shape with the
        declared one (fill when undeclared, raise on conflict) and check
        the declared dtype when an inferred dtype is given.

        Under `_READONLY` (whole-program re-verification) conflicts still
        raise but nothing is written back: verifying must not change the
        program."""
        names = self.op.output(slot)
        if i >= len(names):
            return
        name = names[i]
        var = self.block._find_var_recursive(name)
        if var is None:
            return
        if shape is not None:
            shape = tuple(int(s) for s in shape)
            if var.shape is None:
                if not _READONLY:
                    var.shape = shape
            elif _scalarish(var.shape) and _scalarish(shape):
                # the fluid scalar blur: () and (1,) are used
                # interchangeably for scalars (reference reduce/loss ops
                # declare [1] where jnp produces rank-0); keep the declared
                pass
            else:
                unified = unify_shape(var.shape, shape)
                if unified is None:
                    self.fail(
                        f"output {name!r} declared shape {tuple(var.shape)} "
                        f"does not match inferred shape {shape}",
                        var=name,
                    )
                if not _READONLY:
                    var.shape = unified
        if dtype is not None:
            want = canonical_dtype(dtype)
            if var.dtype != want and _dtype_kind(var.dtype) != _dtype_kind(want):
                # widths legally drift (f64 goldens, bf16 master weights);
                # KIND drift (float vs int vs bool) is a real program bug
                self.fail(
                    f"output {name!r} declared dtype {var.dtype!r} does not "
                    f"match inferred dtype {want!r}",
                    var=name,
                )


def as_infer(rule):
    """Adapt rule(ctx) -> None to the registry's InferFn(op, block)."""

    def infer(op, block):
        rule(InferContext(op, block))

    infer._analysis_rule = rule
    return infer


def register_rule(types: Sequence[str], rule):
    """Attach one rule to several registered op types."""
    fn = as_infer(rule)
    for t in types:
        registry.set_infer(t, fn)
    return fn


# -- generic rule factories (used by ops/* registrations) -------------------

def register_unary_infer(*types, x_slot: str = "X", out_slot: str = "Out",
                         out_dtype: Optional[str] = None):
    """Out has X's shape; dtype follows X unless pinned (compare -> bool)."""

    def rule(ctx: InferContext):
        ctx.set_out(out_slot, ctx.in_shape(x_slot),
                    out_dtype or ctx.in_dtype(x_slot))

    return register_rule(types, rule)


def register_elementwise_infer(*types, out_dtype: Optional[str] = None):
    """Fluid binary broadcasting: Y aligns into X at attr `axis`."""

    def rule(ctx: InferContext):
        xs = ctx.in_shape("X")
        ys = ctx.in_shape("Y")
        dt = out_dtype or ctx.in_dtype("X")
        if xs is None:
            return
        if ys is None:
            ctx.set_out("Out", xs, dt)
            return
        out = fluid_broadcast(xs, ys, ctx.op.attr("axis", -1))
        if out is None:
            ctx.fail(
                f"operands do not broadcast: X{tuple(xs)} vs Y{tuple(ys)} "
                f"at axis={ctx.op.attr('axis', -1)}",
                var=ctx.op.input("X")[0] if ctx.op.input("X") else None,
            )
        ctx.set_out("Out", out, dt)

    return register_rule(types, rule)


def register_reduce_infer(*types):
    def rule(ctx: InferContext):
        xs = ctx.in_shape("X")
        if xs is None:
            return
        if ctx.op.attr("reduce_all", False):
            axes = tuple(range(len(xs)))
        else:
            dim = ctx.op.attr("dim", [0])
            if isinstance(dim, int):
                dim = [dim]
            axes = tuple(sorted(d % len(xs) for d in dim))
        keep = ctx.op.attr("keep_dim", False)
        if keep:
            out = tuple(1 if i in axes else d for i, d in enumerate(xs))
        else:
            out = tuple(d for i, d in enumerate(xs) if i not in axes)
        ctx.set_out("Out", out, ctx.in_dtype("X"))

    return register_rule(types, rule)


def register_state_update_infer(*types):
    """Optimizer-style ops: every `<Slot>Out` output mirrors the `<Slot>`
    input's shape/dtype, and Grad must match Param where both are known."""

    def rule(ctx: InferContext):
        ps = ctx.in_shape("Param")
        gs = ctx.in_shape("Grad")
        if ps is not None and gs is not None and unify_shape(ps, gs) is None:
            ctx.fail(
                f"Grad shape {tuple(gs)} does not match Param shape "
                f"{tuple(ps)}",
                var=ctx.op.input("Param")[0],
            )
        for slot, names in ctx.op.outputs.items():
            src = slot[:-3] if slot.endswith("Out") else None
            if not src or not ctx.op.input(src):
                continue
            for i in range(len(names)):
                ctx.set_out(slot, ctx.in_shape(src, i), ctx.in_dtype(src, i), i=i)

    return register_rule(types, rule)


# --------------------------------------------------------------------------
# structural verifier
# --------------------------------------------------------------------------

def _block_writes(program: Program, block: Block, _seen=None) -> set:
    """All names written by a block's ops, including nested sub-blocks."""
    _seen = _seen if _seen is not None else set()
    if block.idx in _seen:
        return set()
    _seen.add(block.idx)
    out = set()
    for op in block.ops:
        out.update(op.output_arg_names)
        sub = op.attrs.get("sub_block")
        if isinstance(sub, int) and 0 <= sub < len(program.blocks):
            out.update(_block_writes(program, program.blocks[sub], _seen))
    return out


def _initially_defined(block: Block) -> set:
    """Names available before any op runs: data vars (fed), persistables
    (scope state), and parameters, from this block and its ancestors."""
    defined = set()
    blk: Optional[Block] = block
    while blk is not None:
        for name, v in blk.vars.items():
            if v.persistable or v.is_data or isinstance(v, Parameter):
                defined.add(name)
        blk = blk.parent_block
    return defined


def _suggest(type: str) -> str:
    close = registry.suggest_ops(type)
    return f"; did you mean: {', '.join(close)}?" if close else ""


def verify_structure(program: Program) -> List[Diagnostic]:
    """Structural checks over every reachable block (reference: the
    def-use validation OpDesc/BlockDesc did at Append time plus the ir
    Graph sanity checks)."""
    diags: List[Diagnostic] = []
    all_written = set()
    for blk in program.blocks:
        for op in blk.ops:
            all_written.update(op.output_arg_names)
    visited = set()

    def walk(block: Block, defined: set):
        visited.add(block.idx)
        later_writes: Dict[str, int] = {}
        for i, op in enumerate(block.ops):
            for n in op.output_arg_names:
                later_writes.setdefault(n, i)
        param_writes: Dict[str, int] = {}
        for i, op in enumerate(block.ops):
            # (1) every op must have a lowering (or be executor-structural)
            if op.type not in STRUCTURAL_OPS and not registry.has_op(op.type):
                diags.append(Diagnostic(
                    "unregistered_op", SEV_ERROR,
                    f"op type {op.type!r} has no registered lowering"
                    + _suggest(op.type),
                    block=block.idx, op_idx=i, op_type=op.type,
                ))
            # (2) def-before-use / dangling reads
            if op.type != "feed":
                reads = list(op.input_arg_names)
                if op.type == "backward":
                    reads.append(op.attrs.get("loss_name"))
                    reads.extend(op.attrs.get("param_names", []))
                for n in reads:
                    if n is None or n in defined:
                        continue
                    j = later_writes.get(n)
                    if j is not None and j >= i:
                        diags.append(Diagnostic(
                            "use_before_def", SEV_ERROR,
                            f"reads {n!r} which is first written by op #{j} "
                            f"later in the block",
                            block=block.idx, op_idx=i, op_type=op.type, var=n,
                        ))
                    else:
                        known = (n in all_written
                                 or block._find_var_recursive(n) is not None)
                        diags.append(Diagnostic(
                            "dangling_var", SEV_ERROR,
                            (f"reads {n!r} which has no producer on this "
                             f"path and is not feedable state"
                             if known else
                             f"reads {n!r} which is declared nowhere in the "
                             f"program"),
                            block=block.idx, op_idx=i, op_type=op.type, var=n,
                        ))
                    defined.add(n)  # report each missing name once
            # (3) duplicate writes to parameters
            for n in op.output_arg_names:
                v = block._find_var_recursive(n)
                if isinstance(v, Parameter):
                    if n in param_writes:
                        diags.append(Diagnostic(
                            "duplicate_param_write", SEV_ERROR,
                            f"parameter {n!r} already written by op "
                            f"#{param_writes[n]} in this block",
                            block=block.idx, op_idx=i, op_type=op.type, var=n,
                        ))
                    else:
                        param_writes[n] = i
            # (4) sub-block attr sanity + recursion
            sub_idx = op.attrs.get("sub_block")
            if sub_idx is not None:
                ok = (isinstance(sub_idx, int)
                      and 0 <= sub_idx < len(program.blocks)
                      and sub_idx != block.idx)
                if not ok:
                    diags.append(Diagnostic(
                        "orphan_sub_block", SEV_ERROR,
                        f"sub_block attr {sub_idx!r} does not name a valid "
                        f"other block (program has {len(program.blocks)})",
                        block=block.idx, op_idx=i, op_type=op.type,
                    ))
                elif sub_idx in visited:
                    diags.append(Diagnostic(
                        "orphan_sub_block", SEV_ERROR,
                        f"sub_block {sub_idx} is referenced more than once "
                        f"or recursively",
                        block=block.idx, op_idx=i, op_type=op.type,
                    ))
                else:
                    sub = program.blocks[sub_idx]
                    if sub.parent_idx != block.idx:
                        diags.append(Diagnostic(
                            "orphan_sub_block", SEV_WARNING,
                            f"sub_block {sub_idx} has parent_idx "
                            f"{sub.parent_idx}, expected {block.idx}",
                            block=block.idx, op_idx=i, op_type=op.type,
                        ))
                    seed = set(defined)
                    if op.type in _LOOP_OPS:
                        # loop carry: body reads of body-written names see
                        # the previous iteration's value
                        seed |= _block_writes(program, sub)
                    if op.type == "dynamic_rnn":
                        seed |= set(op.attrs.get("step_vars", []))
                        seed |= set(op.attrs.get("mem_vars", []))
                    if op.type == "pipeline":
                        seed.add(op.attrs.get("carry_in"))
                        seed |= set(op.attrs.get("canonical_params", []))
                    walk(sub, seed)
                    # control-flow writes surface to the outer env
                    defined |= _block_writes(program, sub)
            defined.update(op.output_arg_names)
            if op.type == "backward":
                defined.update(op.attrs.get("grad_names", []))

    walk(program.blocks[0], _initially_defined(program.blocks[0]))
    for blk in program.blocks[1:]:
        if blk.idx not in visited and blk.ops:
            diags.append(Diagnostic(
                "orphan_sub_block", SEV_WARNING,
                f"block {blk.idx} is referenced by no op (orphaned "
                f"sub-block with {len(blk.ops)} ops)",
                block=blk.idx,
            ))
    return diags


def verify_feed_fetch(program: Program, feed_names=None, fetch_names=None
                      ) -> List[Diagnostic]:
    """Feed/fetch target existence — the executor knows these at run time."""
    diags: List[Diagnostic] = []
    produced = set()
    for blk in program.blocks:
        for op in blk.ops:
            produced.update(op.output_arg_names)
            if op.type == "backward":
                produced.update(op.attrs.get("grad_names", []))
    feed_names = list(feed_names or [])
    for n in fetch_names or []:
        v = program.blocks[0]._find_var_recursive(n)
        ok = (n in produced or n in feed_names
              or (v is not None and (v.persistable or v.is_data)))
        if not ok:
            diags.append(Diagnostic(
                "fetch_target_missing", SEV_ERROR,
                f"fetch target {n!r} is produced by no op and is not "
                f"feedable state",
                var=n,
            ))
    for n in feed_names:
        found = any(n in blk.vars for blk in program.blocks)
        if not found:
            diags.append(Diagnostic(
                "feed_target_unknown", SEV_WARNING,
                f"feed {n!r} matches no declared variable (dtype/shape "
                f"validation cannot apply)",
                var=n,
            ))
    return diags


# --------------------------------------------------------------------------
# whole-program shape re-inference (FLAGS_verify_program=full)
# --------------------------------------------------------------------------

def verify_shapes(program: Program) -> List[Diagnostic]:
    """Re-run every registered infer fn over the (possibly rewritten)
    program; conflicts become diagnostics instead of raises.  Runs the
    rules read-only: verification never fills/narrows declared shapes."""
    global _READONLY
    diags: List[Diagnostic] = []
    prev, _READONLY = _READONLY, True
    try:
        for blk in program.blocks:
            for i, op in enumerate(blk.ops):
                d = registry.get_op_def_or_none(op.type)
                if d is None or d.infer is None:
                    continue
                try:
                    d.infer(op, blk)
                except StaticAnalysisError as e:
                    diags.append(Diagnostic(
                        "shape_dtype", SEV_ERROR, str(e),
                        block=blk.idx, op_idx=i, op_type=op.type,
                    ))
    finally:
        _READONLY = prev
    return diags


def infer_coverage(programs: Sequence[Program]) -> Dict[str, Any]:
    """Fraction of op TYPES appearing in `programs` that have an infer fn
    (the `analysis.infer_coverage_frac` proof for the model zoo)."""
    types = set()
    n_ops = 0
    n_ops_covered = 0
    for p in programs:
        for blk in p.blocks:
            for op in blk.ops:
                if op.type in STRUCTURAL_OPS:
                    continue
                types.add(op.type)
                n_ops += 1
                d = registry.get_op_def_or_none(op.type)
                if d is not None and d.infer is not None:
                    n_ops_covered += 1
    covered = sorted(
        t for t in types
        if (registry.get_op_def_or_none(t) is not None
            and registry.get_op_def_or_none(t).infer is not None)
    )
    missing = sorted(types - set(covered))
    frac = (len(covered) / len(types)) if types else 1.0
    return {
        "covered_types": covered,
        "missing_types": missing,
        "frac": frac,
        "op_frac": (n_ops_covered / n_ops) if n_ops else 1.0,
    }


# --------------------------------------------------------------------------
# hazard lints
# --------------------------------------------------------------------------

def lint_donation(program: Program) -> List[Diagnostic]:
    """In-place persistable updates (the executor DONATES these buffers)
    that are read again later in the same block: the reader silently
    observes post-update state, and under buffer donation the pre-update
    value no longer exists — a rewrite reordering either op changes
    numerics without any error."""
    diags: List[Diagnostic] = []
    for blk in program.blocks:
        inplace_at: Dict[str, Tuple[int, str]] = {}
        for i, op in enumerate(blk.ops):
            in_names = set(op.input_arg_names)
            for n in op.output_arg_names:
                if n not in in_names or n in inplace_at:
                    continue
                v = blk._find_var_recursive(n)
                if v is not None and v.persistable:
                    inplace_at[n] = (i, op.type)
        for i, op in enumerate(blk.ops):
            for n in set(op.input_arg_names):
                hit = inplace_at.get(n)
                if hit is not None and hit[0] < i:
                    diags.append(Diagnostic(
                        "donation_hazard", SEV_WARNING,
                        f"reads {n!r} after op #{hit[0]} ({hit[1]}) updated "
                        f"it in place; the donated pre-update buffer is "
                        f"gone and pass reordering would change numerics",
                        block=blk.idx, op_idx=i, op_type=op.type, var=n,
                    ))
    return diags


def lint_recompile(program: Program) -> List[Diagnostic]:
    """Feed vars whose NON-batch dims are dynamic: every distinct feed
    shape is a fresh executable (compile-cache key includes the feed
    signature), so such feeds never amortize — bucket/pad them instead
    (what the LoD padded carrier already does for its time dim)."""
    diags: List[Diagnostic] = []
    for v in program.list_vars():
        if not v.is_data or v.shape is None:
            continue
        allowed = 2 if v.lod_level >= 1 else 1  # batch (+ bucketed time)
        dyn = [i for i, d in enumerate(v.shape) if d == DYN and i >= allowed]
        if dyn:
            diags.append(Diagnostic(
                "recompile_hazard", SEV_WARNING,
                f"feed var {v.name!r} shape {tuple(v.shape)} has dynamic "
                f"non-batch dims {dyn}: every distinct feed shape compiles "
                f"a fresh executable; pad to fixed shape buckets",
                block=v.block.idx, var=v.name,
            ))
    return diags


def lint_determinism(program: Program) -> List[Diagnostic]:
    """RNG-consuming ops in a program with no random_seed: run-to-run
    results are irreproducible and resume-replay cannot be bit-exact."""
    if program.random_seed is not None:
        return []
    diags: List[Diagnostic] = []
    for blk in program.blocks:
        for i, op in enumerate(blk.ops):
            if op.type not in RNG_OPS:
                continue
            if op.type == "dropout":
                if op.attr("is_test", False) or op.attr("fix_seed", False):
                    continue
            elif op.attr("seed", 0):
                continue
            out = op.output_arg_names[0] if op.output_arg_names else None
            diags.append(Diagnostic(
                "nondeterministic_rng", SEV_WARNING,
                f"RNG op {op.type!r} with no op seed in a program with no "
                f"random_seed: results are not reproducible",
                block=blk.idx, op_idx=i, op_type=op.type, var=out,
            ))
    return diags


def collective_signature(program: Program) -> List[Tuple]:
    """Static order of collective-issuing ops, with their mesh axis and
    whether they sit under divergent (conditional) control flow."""
    sig: List[Tuple] = []

    def walk(block: Block, divergent: bool, seen):
        if block.idx in seen:
            return
        seen.add(block.idx)
        for op in block.ops:
            if op.type in COLLECTIVE_OPS:
                axis = op.attr(COLLECTIVE_OPS[op.type], None)
                sig.append((op.type, axis, block.idx, divergent))
            sub = op.attrs.get("sub_block")
            if isinstance(sub, int) and 0 <= sub < len(program.blocks):
                walk(program.blocks[sub],
                     divergent or op.type == "conditional_block", seen)

    walk(program.blocks[0], False, set())
    return sig


def lint_collective_order(programs: Sequence[Program]) -> List[Diagnostic]:
    """All ranks must issue collectives in the same static order (the
    build-time complement of the PR-4 runtime watchdog).  Single-program
    mode flags collectives under divergent control flow; multi-program
    mode additionally diffs the per-rank signatures."""
    diags: List[Diagnostic] = []
    sigs = [collective_signature(p) for p in programs]
    for (op_type, axis, blk_idx, divergent) in sigs[0]:
        if divergent:
            diags.append(Diagnostic(
                "collective_order", SEV_WARNING,
                f"collective op {op_type!r} (axis {axis!r}) sits under a "
                f"conditional_block: ranks whose predicates diverge will "
                f"issue collectives in different orders and deadlock",
                block=blk_idx, op_type=op_type,
            ))
    base = [(t, a) for (t, a, _, _) in sigs[0]]
    for rank, sig in enumerate(sigs[1:], start=1):
        other = [(t, a) for (t, a, _, _) in sig]
        if other == base:
            continue
        n = min(len(base), len(other))
        at = next((i for i in range(n) if base[i] != other[i]), n)
        ours = base[at] if at < len(base) else None
        theirs = other[at] if at < len(other) else None
        diags.append(Diagnostic(
            "collective_order", SEV_ERROR,
            f"rank-program {rank} issues collectives in a different static "
            f"order: position {at} is {theirs} vs rank 0's {ours} — this "
            f"deadlocks the gang at runtime",
            op_type=theirs[0] if theirs else (ours[0] if ours else None),
        ))
    return diags


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------

LEVELS = ("off", "structural", "full")


def verify_program(program: Program, level: str = "structural",
                   feed_names=None, fetch_names=None,
                   sibling_programs: Optional[Sequence[Program]] = None
                   ) -> List[Diagnostic]:
    """Run the analysis suite at `level`; returns diagnostics (errors and
    warnings).  `structural` = verifier (+ feed/fetch when given); `full`
    adds whole-program shape re-inference and the hazard lints."""
    if level in (None, "", "off"):
        return []
    if level not in LEVELS:
        raise ValueError(f"verify_program: unknown level {level!r}; "
                         f"one of {LEVELS}")
    diags = verify_structure(program)
    if feed_names or fetch_names:
        diags += verify_feed_fetch(program, feed_names, fetch_names)
    if level == "full":
        diags += verify_shapes(program)
        diags += lint_donation(program)
        diags += lint_recompile(program)
        diags += lint_determinism(program)
        diags += lint_collective_order(
            [program] + list(sibling_programs or []))
        cov = infer_coverage([program])
        _MON.gauge("analysis.infer_coverage_frac").set(cov["frac"])
    _MON.counter("analysis.verify_runs").inc()
    for d in diags:
        _MON.counter(f"analysis.diag.{d.code}").inc()
    return diags


def check_program(program: Program, level: str = "structural",
                  feed_names=None, fetch_names=None,
                  sibling_programs=None) -> List[Diagnostic]:
    """`verify_program`, raising `ProgramVerificationError` on any
    error-severity diagnostic.  Returns the (warning-only) diagnostics."""
    diags = verify_program(program, level, feed_names, fetch_names,
                           sibling_programs)
    errors = [d for d in diags if d.severity == SEV_ERROR]
    if errors:
        lines = "\n".join(f"  {d}" for d in errors)
        raise ProgramVerificationError(
            f"program verification failed ({len(errors)} error(s)):\n{lines}",
            errors,
        )
    return diags
