"""Unique name generator (reference: python/paddle/fluid/unique_name.py).

Keeps per-prefix counters inside a guard-able generator so cloned programs and
tests get reproducible names.
"""
from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids = defaultdict(int)

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return f"{self.prefix}{key}_{tmp}"


generator = UniqueNameGenerator()

# name_scope support (reference unique_name.py name_scope stack): a path of
# scope names prefixes every generated name WITHOUT resetting counters, and
# repeated sibling scopes dedup ("encoder", "encoder_1", ...)
_scope_stack: list = []
_scope_children: dict = defaultdict(lambda: defaultdict(int))


def generate(key: str) -> str:
    name = generator(key)
    if _scope_stack:
        return "/".join(_scope_stack) + "/" + name
    return name


@contextlib.contextmanager
def name_scope_guard(prefix: str):
    parent = "/".join(_scope_stack)
    n = _scope_children[parent][prefix]
    _scope_children[parent][prefix] += 1
    unique = prefix if n == 0 else f"{prefix}_{n}"
    _scope_stack.append(unique)
    try:
        yield
    finally:
        _scope_stack.pop()


@contextlib.contextmanager
def guard(new_prefix: str = ""):
    """Swap in a fresh generator (used by Program.clone and tests)."""
    global generator
    old = generator
    generator = UniqueNameGenerator(new_prefix)
    try:
        yield
    finally:
        generator = old


def switch(new_generator=None):
    """reference unique_name.switch: swap the generator state, returning
    the old one (tests isolate name streams with it)."""
    global generator
    old = generator
    generator = new_generator if new_generator is not None else UniqueNameGenerator()
    return old
