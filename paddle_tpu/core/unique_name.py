"""Unique name generator (reference: python/paddle/fluid/unique_name.py).

Keeps per-prefix counters inside a guard-able generator so cloned programs and
tests get reproducible names.
"""
from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids = defaultdict(int)

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return f"{self.prefix}{key}_{tmp}"


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return generator(key)


@contextlib.contextmanager
def guard(new_prefix: str = ""):
    """Swap in a fresh generator (used by Program.clone and tests)."""
    global generator
    old = generator
    generator = UniqueNameGenerator(new_prefix)
    try:
        yield
    finally:
        generator = old
