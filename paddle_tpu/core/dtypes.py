"""Dtype vocabulary for the program IR.

The reference keeps a VarType.Type enum in framework.proto:105 (LOD_TENSOR,
FP32, INT64, ...).  We keep a string dtype vocabulary that maps 1:1 onto JAX
dtypes; bf16 is first-class because it is the native TPU matmul type.
"""
from __future__ import annotations

import numpy as np

try:  # jax.numpy gives us bfloat16
    import jax.numpy as jnp

    _BFLOAT16 = jnp.bfloat16
except Exception:  # pragma: no cover
    _BFLOAT16 = np.float32

# canonical name -> numpy-compatible dtype object
_DTYPES = {
    "float16": np.float16,
    "bfloat16": _BFLOAT16,
    "float32": np.float32,
    "float64": np.float64,
    "int8": np.int8,
    "uint8": np.uint8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}

_ALIASES = {
    "fp16": "float16",
    "bf16": "bfloat16",
    "fp32": "float32",
    "fp64": "float64",
    "float": "float32",
    "double": "float64",
    "int": "int32",
    "long": "int64",
}


def canonical_dtype(dtype) -> str:
    """Normalise any dtype spec (str, np.dtype, jnp dtype) to a canonical name."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _DTYPES:
            return name
        # fall through to numpy parsing for things like 'float32'
    try:
        name = np.dtype(dtype).name
    except TypeError:
        name = str(dtype)
    if name == "bfloat16" or "bfloat16" in name:
        return "bfloat16"
    name = _ALIASES.get(name, name)
    if name not in _DTYPES:
        raise ValueError(f"unsupported dtype: {dtype!r}")
    return name


def as_np_dtype(dtype):
    """Return the numpy/jax dtype object for a canonical or loose dtype spec."""
    return _DTYPES[canonical_dtype(dtype)]


def is_floating(dtype) -> bool:
    return canonical_dtype(dtype) in ("float16", "bfloat16", "float32", "float64")


def is_integer(dtype) -> bool:
    return canonical_dtype(dtype) in ("int8", "uint8", "int16", "int32", "int64")
