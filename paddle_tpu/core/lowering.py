"""Block -> JAX lowering.

This replaces the reference's per-op interpreter hot loop
(`framework/executor.cc:416-421`: `for op in ctx->ops_: op->Run(...)`).
Instead of running kernels, `run_ops` symbolically interprets the op list
once inside a jax trace, producing a single XLA computation per block —
the seam SURVEY.md identifies at `executor.cc:337` (nGraph subgraph engine)
taken to its limit: the *whole* block is the subgraph.

The `backward` op (emitted by core/autodiff.py) splits the op list into a
forward segment and an update segment; gradients are obtained with `jax.vjp`
over the re-interpreted forward segment, so XLA sees forward+backward+update
as one fused program.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..monitor import MONITOR as _MON
from .program import Block, Operator
from .registry import get_op_def


class LoweringContext:
    """Per-trace state: RNG threading, train/eval mode, mesh info.

    JAX PRNG is explicit; the reference's global curand state maps to a key
    threaded through the trace.  Each RNG-consuming op calls `next_key()`.
    The final key is returned from the compiled function and stored back in
    the scope, so randomness advances across `Executor.run` calls.
    """

    def __init__(self, key, is_test: bool = False, mesh=None, platform: Optional[str] = None):
        self.key = key
        self.is_test = is_test
        self.mesh = mesh
        # target backend ("tpu"/"cpu"); lowerings that have a Pallas TPU
        # kernel (fused_attention) pick it here and fall back to plain jnp
        # math elsewhere so CPU tests and virtual meshes still run
        self.platform = platform
        # current var env, set by run_ops; control-flow lowerings read it to
        # capture outer values and compute loop-carried state
        self.env: Dict[str, Any] = {}
        # set by run_block_with_backward while sparse-grad taps are active
        self.sparse_taps = None
        # backward-overlapped dp gradient all-reduce: when the executor runs
        # the step inside a manual (shard_map) dp region, this holds the
        # bucketed-psum callable from parallel.distributed.make_grad_sync;
        # _run_one_backward_region applies it to the assembled grads so the
        # optimizer segment consumes globally-reduced gradients
        self.grad_sync = None
        # fetch targets of the step being traced (set by the executor):
        # lowerings that can skip optional output slots on a fused path
        # (e.g. layer_norm Mean/Variance under FLAGS_use_pallas) consult
        # this so a fetched slot keeps the composite that populates it
        self.fetch_names = ()
        # BuildStrategy.memory_optimize: rematerialize the forward during
        # backward (jax.checkpoint) instead of keeping activations
        self.remat = False

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub


# Ops handled by the executor itself, not by a registered lowering.
_STRUCTURAL_OPS = ("feed", "fetch", "backward")


def run_ops(ctx: LoweringContext, ops: List[Operator], env: Dict[str, Any]) -> Dict[str, Any]:
    """Interpret `ops` over `env` (var name -> traced jax value), in order.

    Op-level provenance (ISSUE 8): each op's emission is wrapped in
    `jax.named_scope("op<idx>:<type>")`, so XLA op metadata — and with it
    device profiles, HLO dumps, and the merged gang traces — maps every
    fused region back to the ProgramDesc op(s) that produced it.  Pure
    trace-time cost: the scope name lands in the jaxpr/HLO, nothing runs
    per step."""
    # per-op lower counts run at TRACE time only (this loop is the trace),
    # so the monitor's per-program op census costs nothing at execution
    mon_on = _MON.enabled
    for idx, op in enumerate(ops):
        if op.type in _STRUCTURAL_OPS:
            raise RuntimeError(
                f"structural op {op.type!r} reached the lowering interpreter; "
                "the executor must handle it"
            )
        with jax.named_scope(f"op{idx}:{op.type}"):
            lower_one(ctx, op, env)
        if mon_on:
            _MON.counter("lowering.ops_total").inc()
            _MON.counter("lowering.op." + op.type).inc()
    return env


def lower_one(ctx: LoweringContext, op: Operator, env: Dict[str, Any]) -> None:
    opdef = get_op_def(op.type, op=op, block=op.block)
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n not in env:
                raise KeyError(
                    f"op {op.type!r} reads {n!r} which is not defined; "
                    "feed it, initialize it via the startup program, or check op order"
                )
            vals.append(env[n])
        ins[slot] = vals
    ctx.env = env
    outs = opdef.lower(ctx, op, ins)
    if "__env_update__" in outs:  # control-flow ops write vars wholesale
        env.update(outs.pop("__env_update__"))
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        if len(vals) != len(names):
            raise RuntimeError(
                f"op {op.type!r} slot {slot!r}: lowering returned {len(vals)} "
                f"values for {len(names)} outputs"
            )
        for n, v in zip(names, vals):
            env[n] = v


# Trace-time report of the last lowered backward (inspection/test surface;
# static facts only — which params took the SelectedRows path).
LAST_TRACE_REPORT: Dict[str, Any] = {}


class SparseTapCollector:
    """Collects is_sparse lookup_table 'taps' so embedding-table gradients
    come out as SelectedRows instead of dense V×D arrays.

    Phase "record": the forward is abstractly evaluated (jax.eval_shape) and
    each sparse lookup registers (w_name, ids_name, out_shape/dtype).
    Phase "inject": the real vjp'd forward adds a zero `delta` to each
    tapped lookup output (before padding_idx masking); d(loss)/d(delta) is
    exactly the per-row gradient slab, and the ids come out of the aux env
    by var name — no dense table-shaped cotangent ever exists.
    """

    def __init__(self, params):
        self.params = set(params)
        self.taps: list = []  # (w_name, ids_name, shape, dtype)
        self.mode = "record"
        self.deltas: Optional[list] = None
        self.i = 0

    def tap(self, w_name: str, ids_name: str, out):
        if w_name not in self.params:
            return out
        if self.mode == "record":
            self.taps.append((w_name, ids_name, out.shape, out.dtype))
            return out
        d = self.deltas[self.i]
        self.i += 1
        return out + d


def run_block_with_backward(ctx: LoweringContext, ops: List[Operator], env: Dict[str, Any]) -> Dict[str, Any]:
    """Interpret a block that may contain `backward` ops.

    Forward ops re-run inside jax.vjp so forward+backward fuse into one XLA
    program; the aux env carries every forward intermediate out of the vjp
    (XLA keeps only what is actually used downstream).

    Multiple backward regions (calc_gradient + minimize in one program) are
    supported: each region differentiates the full op prefix before it —
    values produced by EARLIER regions (e.g. their grads) enter later
    regions as constants (stop-gradient), matching the reference's
    grad-of-grad-free semantics.  XLA CSEs the re-interpreted prefixes.
    """
    splits = [i for i, op in enumerate(ops) if op.type == "backward"]
    if not splits:
        return run_ops(ctx, ops, env)

    report_sparse: List[str] = []
    # every region re-interprets its op prefix FROM THE BLOCK-START env
    # (so stateful-name ops apply exactly once no matter how many regions
    # re-trace them), with earlier regions' grads injected as constants;
    # the RNG stream is pinned so dropout masks etc. are IDENTICAL across
    # regions — all grads describe one forward pass
    key0 = ctx.key
    start_env = dict(env)
    grads_so_far: Dict[str, Any] = {}
    for si in splits:
        ctx.key = key0
        env = _run_one_backward_region(ctx, ops, si, start_env, grads_so_far,
                                       report_sparse)
    LAST_TRACE_REPORT.clear()
    LAST_TRACE_REPORT["sparse_grad_params"] = report_sparse
    tail_ops = ops[splits[-1] + 1:]
    return run_ops(ctx, tail_ops, env)


def _run_one_backward_region(ctx: LoweringContext, ops: List[Operator], split: int,
                             start_env: Dict[str, Any], grads_so_far: Dict[str, Any],
                             report_sparse: List[str]) -> Dict[str, Any]:
    bw = ops[split]
    loss_name = bw.attrs["loss_name"]
    param_names: List[str] = list(bw.attrs["param_names"])
    grad_names: List[str] = list(bw.attrs["grad_names"])
    fwd_ops = [o for o in ops[:split] if o.type != "backward"]

    base_env = dict(start_env)
    base_env.update(grads_so_far)
    env = base_env

    for p in param_names:
        if p not in env:
            raise KeyError(f"backward: parameter {p!r} not initialized (run the startup program)")

    sparse_names = [n for n in bw.attrs.get("sparse_param_names", []) if n in param_names]
    dense_names = [p for p in param_names if p not in sparse_names]
    report_sparse.extend(n for n in sparse_names if n not in report_sparse)

    coll = None
    if sparse_names:
        # Phase "record": abstract-eval the forward to enumerate sparse taps
        # (cheap — no compute, no compile).  RNG key is saved/restored so the
        # probe doesn't advance the real stream.
        coll = SparseTapCollector(sparse_names)
        ctx.sparse_taps = coll
        saved_key = ctx.key

        def probe(params):
            e = dict(base_env)
            e.update(params)
            run_ops(ctx, fwd_ops, e)
            return 0

        jax.eval_shape(probe, {p: env[p] for p in param_names})
        ctx.key = saved_key
        coll.mode = "inject"

    def fwd(params: Dict[str, Any], deltas: Dict[str, Any]):
        if coll is not None:
            coll.deltas = [deltas[f"__tap{i}"] for i in range(len(coll.taps))]
            coll.i = 0
        e = dict(base_env)
        e.update(params)
        e = run_ops(ctx, fwd_ops, e)
        loss = e[loss_name]
        return loss, e

    primal_params = {p: env[p] for p in dense_names}
    deltas0 = {}
    if coll is not None:
        for i, (_, _, shape, dtype) in enumerate(coll.taps):
            deltas0[f"__tap{i}"] = jnp.zeros(shape, dtype)

    fwd_fn = jax.checkpoint(fwd) if ctx.remat else fwd
    loss, vjp_fn, env_after = jax.vjp(fwd_fn, primal_params, deltas0, has_aux=True)
    (grads, dtaps) = vjp_fn(jnp.ones_like(loss))

    # merge the region's fresh intermediates over the incoming env so
    # earlier regions' grads survive for downstream consumers
    env = dict(env)
    env.update(env_after)
    ctx.sparse_taps = None
    named = []
    for p, g in zip(param_names, grad_names):
        if p in sparse_names:
            gval = _gather_sparse_grad(p, coll, dtaps, env)
        else:
            gval = grads[p]
            if gval is None:  # non-float param leaked in; treat as zero
                gval = jnp.zeros_like(env[p])
        named.append((g, gval))
    if ctx.grad_sync is not None:
        synced = ctx.grad_sync(named)
        named = [(g, synced.get(g, v)) for g, v in named]
    for g, gval in named:
        env[g] = gval
        grads_so_far[g] = gval
    return env


def _gather_sparse_grad(param: str, coll: "SparseTapCollector", dtaps: Dict[str, Any], env: Dict[str, Any]):
    """Assemble a SelectedRows grad for `param` from its lookup taps: rows
    are the (traced) ids read from the aux env, values the delta-cotangents.
    Multiple lookups of one table concatenate (duplicates are legal and
    merged by the optimizer's MergeAdd)."""
    from ..ops.common import flatten_lookup_ids
    from .selected_rows import SelectedRows

    height = env[param].shape[0]
    dim = env[param].shape[1] if len(env[param].shape) > 1 else 1
    rows_parts = []
    vals_parts = []
    for i, (w_name, ids_name, _, _) in enumerate(coll.taps):
        if w_name != param:
            continue
        flat = flatten_lookup_ids(env[ids_name])
        rows_parts.append(flat.reshape(-1).astype(jnp.int32))
        vals_parts.append(dtaps[f"__tap{i}"].reshape(-1, dim))
    if not rows_parts:
        # table never actually looked up in the pruned program: empty slab
        return SelectedRows(
            jnp.zeros((0,), jnp.int32),
            jnp.zeros((0, dim), env[param].dtype),
            height,
        )
    return SelectedRows(
        jnp.concatenate(rows_parts), jnp.concatenate(vals_parts), height
    )
