"""Block -> JAX lowering.

This replaces the reference's per-op interpreter hot loop
(`framework/executor.cc:416-421`: `for op in ctx->ops_: op->Run(...)`).
Instead of running kernels, `run_ops` symbolically interprets the op list
once inside a jax trace, producing a single XLA computation per block —
the seam SURVEY.md identifies at `executor.cc:337` (nGraph subgraph engine)
taken to its limit: the *whole* block is the subgraph.

The `backward` op (emitted by core/autodiff.py) splits the op list into a
forward segment and an update segment; gradients are obtained with `jax.vjp`
over the re-interpreted forward segment, so XLA sees forward+backward+update
as one fused program.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from .program import Block, Operator
from .registry import get_op_def


class LoweringContext:
    """Per-trace state: RNG threading, train/eval mode, mesh info.

    JAX PRNG is explicit; the reference's global curand state maps to a key
    threaded through the trace.  Each RNG-consuming op calls `next_key()`.
    The final key is returned from the compiled function and stored back in
    the scope, so randomness advances across `Executor.run` calls.
    """

    def __init__(self, key, is_test: bool = False, mesh=None):
        self.key = key
        self.is_test = is_test
        self.mesh = mesh
        # current var env, set by run_ops; control-flow lowerings read it to
        # capture outer values and compute loop-carried state
        self.env: Dict[str, Any] = {}

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub


# Ops handled by the executor itself, not by a registered lowering.
_STRUCTURAL_OPS = ("feed", "fetch", "backward")


def run_ops(ctx: LoweringContext, ops: List[Operator], env: Dict[str, Any]) -> Dict[str, Any]:
    """Interpret `ops` over `env` (var name -> traced jax value), in order."""
    for op in ops:
        if op.type in _STRUCTURAL_OPS:
            raise RuntimeError(
                f"structural op {op.type!r} reached the lowering interpreter; "
                "the executor must handle it"
            )
        lower_one(ctx, op, env)
    return env


def lower_one(ctx: LoweringContext, op: Operator, env: Dict[str, Any]) -> None:
    opdef = get_op_def(op.type)
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n not in env:
                raise KeyError(
                    f"op {op.type!r} reads {n!r} which is not defined; "
                    "feed it, initialize it via the startup program, or check op order"
                )
            vals.append(env[n])
        ins[slot] = vals
    ctx.env = env
    outs = opdef.lower(ctx, op, ins)
    if "__env_update__" in outs:  # control-flow ops write vars wholesale
        env.update(outs.pop("__env_update__"))
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        if len(vals) != len(names):
            raise RuntimeError(
                f"op {op.type!r} slot {slot!r}: lowering returned {len(vals)} "
                f"values for {len(names)} outputs"
            )
        for n, v in zip(names, vals):
            env[n] = v


def find_backward_split(ops: List[Operator]) -> Optional[int]:
    for i, op in enumerate(ops):
        if op.type == "backward":
            return i
    return None


def run_block_with_backward(ctx: LoweringContext, ops: List[Operator], env: Dict[str, Any]) -> Dict[str, Any]:
    """Interpret a block that may contain one `backward` op.

    Forward ops re-run inside jax.vjp so forward+backward fuse into one XLA
    program; the aux env carries every forward intermediate out of the vjp
    (XLA keeps only what is actually used downstream).
    """
    split = find_backward_split(ops)
    if split is None:
        return run_ops(ctx, ops, env)

    bw = ops[split]
    loss_name = bw.attrs["loss_name"]
    param_names: List[str] = list(bw.attrs["param_names"])
    grad_names: List[str] = list(bw.attrs["grad_names"])
    fwd_ops = ops[:split]
    tail_ops = ops[split + 1 :]

    base_env = dict(env)

    def fwd(params: Dict[str, Any]):
        e = dict(base_env)
        e.update(params)
        e = run_ops(ctx, fwd_ops, e)
        loss = e[loss_name]
        return loss, e

    primal_params = {}
    for p in param_names:
        if p not in env:
            raise KeyError(f"backward: parameter {p!r} not initialized (run the startup program)")
        primal_params[p] = env[p]

    loss, vjp_fn, env_after = jax.vjp(fwd, primal_params, has_aux=True)
    (grads,) = vjp_fn(jnp.ones_like(loss))

    env = env_after
    for p, g in zip(param_names, grad_names):
        gval = grads[p]
        if gval is None:  # non-float param leaked in; treat as zero
            gval = jnp.zeros_like(env[p])
        env[g] = gval
    return run_ops(ctx, tail_ops, env)
