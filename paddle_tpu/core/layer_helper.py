"""LayerHelper: shared plumbing for layer functions.

Reference: python/paddle/fluid/layer_helper.py — creates parameters in both
the main and startup programs, appends ops, applies the `act` attr.
"""
from __future__ import annotations

from typing import Optional

from . import unique_name
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr
from .program import default_main_program, default_startup_program

# Mixed-precision master-weight policy (round-5 fix, docs/perf_r05.md):
# trainable parameters requested in a low-precision float are CREATED as
# float32 masters — every consuming op lowers through match_dtype, which
# casts the master to the activation dtype inside the compiled step, so the
# program still computes in bf16 on the MXU.  Without this the r4 bf16
# models created bf16 params, whose bf16 Adam beta-pow accumulators rounded
# 0.999 -> 1.0 and made lr_t = lr*sqrt(1-b2p)/(1-b1p) identically ZERO:
# bf16+Adam params silently never trained.  Toggle for experiments only.
_MASTER_WEIGHTS = True
_LOW_PRECISION = ("bfloat16", "float16", "fp16", "bf16")


def _master_dtype(dtype):
    if _MASTER_WEIGHTS and str(dtype) in _LOW_PRECISION:
        return "float32"
    return dtype


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def main_block(self):
        return self.main_program.current_block()

    def append_op(self, *args, **kw):
        return self.main_block.append_op(*args, **kw)

    def create_parameter(self, attr, shape, dtype, is_bias: bool = False, default_initializer=None):
        import copy

        # copy so a ParamAttr reused across layers doesn't get a name pinned
        # by the first layer (reference layer_helper_base.py does the same)
        attr = copy.copy(ParamAttr._to_attr(attr))
        if attr.name is None:
            attr.name = unique_name.generate(f"{self.name}.w" if not is_bias else f"{self.name}.b")
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()
        shape = [int(s) for s in shape]
        dtype = _master_dtype(dtype)
        # parameter lives in the main program; its init op lives in startup
        param = self.main_program.global_block().create_parameter(
            attr.name,
            shape=shape,
            dtype=dtype,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
        )
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        startup_block = self.startup_program.global_block()
        sv = startup_block.create_var(attr.name, shape=shape, dtype=dtype, persistable=True)
        init(sv, startup_block)
        return param

    def create_variable_for_type_inference(self, dtype, shape=None):
        return self.main_block.create_var(
            unique_name.generate(f"{self.name}.tmp"), shape=shape, dtype=dtype
        )

    def append_activation(self, out):
        act = self.kwargs.get("act")
        if act is None:
            return out
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        res = self.create_variable_for_type_inference(out.dtype, shape=out.shape)
        self.append_op(act_type, inputs={"X": [out.name]}, outputs={"Out": [res.name]}, attrs=act)
        return res

    def append_bias_op(self, out, bias_attr, shape, dim_start: int = 1):
        if bias_attr is False:
            return out
        size = shape[-1] if isinstance(shape, (list, tuple)) else shape
        b = self.create_parameter(bias_attr, [int(size)], out.dtype, is_bias=True)
        res = self.create_variable_for_type_inference(out.dtype, shape=out.shape)
        self.append_op(
            "elementwise_add",
            inputs={"X": [out.name], "Y": [b.name]},
            outputs={"Out": [res.name]},
            attrs={"axis": dim_start},
        )
        return res
