"""Named lock registry: every framework lock has a name and a rank.

The framework is a genuinely multi-threaded system — serving workers,
batcher, heartbeat/watchdog threads, pipeline prefetch, monitor loggers —
and raw ``threading.Lock()`` objects give a reviewer nothing to reason
about: no identity in a stack dump, no declared order, no contention
signal.  Every lock the framework creates goes through this module
instead:

    from paddle_tpu.core.locks import named_lock
    self._lock = named_lock("serving.registry", rank=14, reentrant=True)

``name`` is a stable dotted identifier (it keys telemetry counters and
appears in every diagnostic); ``rank`` declares the lock's position in
the process-wide partial order: **a thread may only acquire a lock whose
rank is strictly greater than every lock it already holds** (re-entrant
same-name acquisition through a ``reentrant=True`` lock is exempt).  Any
two locks ever nested must therefore have distinct ranks, ascending
outside-in.  The declared order is enforced statically by
``tools/concurrency_lint.py`` (which parses every ``named_lock`` site and
every ``with``/``acquire`` nesting in ``paddle_tpu/``) and observed at
runtime by the opt-in telemetry below.  The full rank table lives in
``docs/static_analysis.md``.

Runtime half (both opt-in, a module-global flag branch when off):

* ``FLAGS_lock_telemetry`` — per-lock monitor counters
  ``lock.<name>.acquires`` / ``.contended`` / ``.wait_us`` / ``.hold_us``
  plus ``lock.order_inversions`` when an acquisition inverts the declared
  ranks.  ``perf_report --check --max-lock-wait-frac`` gates the
  wait/(wait+hold) contention fraction from these counters.  Monitor-
  internal locks opt out (``telemetry=False``): instrumenting the lock a
  Counter.inc takes would recurse into Counter.inc.

* ``FLAGS_lock_timeout_s`` — every blocking ``acquire`` gets a deadline;
  on expiry a classified ``errors.LockTimeoutError`` (FatalError) names
  BOTH sides of the suspected deadlock — the wanted lock and every lock
  the thread holds, each with its declared rank — instead of hanging a
  worker forever.

Disabled-mode contract (the hot-path budget, same deal as the monitor):
``acquire``/``__enter__`` are one module-global branch plus the raw lock
primitive — no per-thread bookkeeping, no counters, no clock reads;
``release`` adds one thread-local read and two falsy checks (cleanup must
not be gated on the CURRENT flag state, or a flag toggled mid-hold
strands bookkeeping).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["NamedLock", "NamedCondition", "named_lock", "named_rlock",
           "named_condition", "lock_ranks", "held_locks"]

# name -> (rank, reentrant); one rank per name, process-wide.  Multiple
# instances may share a name (e.g. every monitor Counter's lock is
# "monitor.counter"): they are one lock *class* in the declared order.
_RANKS: Dict[str, Tuple[int, bool]] = {}
# Guards _RANKS/_COUNTERS registration only (module-internal; creation
# time, never the acquire hot path).  Deliberately a raw lock: it orders
# nothing user-visible and the lint skips this file.
_REG_GUARD = threading.Lock()

_TLS = threading.local()

# Config cache, refreshed from the flag registry (flags.set_flags hooks
# back into refresh_from_flags; import-time init reads the env-seeded
# values).  _ACTIVE gates ALL slow-path work with one global load.
_TELEMETRY = False
_TIMEOUT_S = 0.0
_ACTIVE = False

_MON_REF = None  # lazily bound monitor singleton (avoids an import cycle:
# monitor.core builds its own locks through this module)

# per-name cached counter tuple (acquires, contended, wait_us, hold_us)
_COUNTERS: Dict[str, tuple] = {}


def refresh_from_flags():
    """Re-read FLAGS_lock_telemetry / FLAGS_lock_timeout_s (called by
    flags.set_flags; import below seeds from the env)."""
    global _TELEMETRY, _TIMEOUT_S, _ACTIVE
    from ..flags import flag

    _TELEMETRY = bool(flag("FLAGS_lock_telemetry"))
    _TIMEOUT_S = float(flag("FLAGS_lock_timeout_s"))
    _ACTIVE = _TELEMETRY or _TIMEOUT_S > 0


def _mon():
    global _MON_REF
    if _MON_REF is None:
        from ..monitor import MONITOR

        _MON_REF = MONITOR
    return _MON_REF


def _counters(name: str) -> tuple:
    c = _COUNTERS.get(name)
    if c is None:
        # counters are created OUTSIDE _REG_GUARD: Monitor.counter takes
        # the monitor.registry lock, whose miss path creates a named lock
        # and so takes _REG_GUARD — holding _REG_GUARD here would invert
        # that order (a deadlock this module's own lint would flag).
        # Monitor.counter is idempotent, so a racing double-create is fine.
        mon = _mon()
        tup = (mon.counter(f"lock.{name}.acquires"),
               mon.counter(f"lock.{name}.contended"),
               mon.counter(f"lock.{name}.wait_us"),
               mon.counter(f"lock.{name}.hold_us"))
        with _REG_GUARD:
            c = _COUNTERS.setdefault(name, tup)
    return c


def _held() -> list:
    h = getattr(_TLS, "held", None)
    if h is None:
        h = _TLS.held = []
    return h


def held_locks() -> List[Tuple[str, int]]:
    """[(name, rank)] of the named locks THIS thread currently holds —
    only tracked while telemetry or a lock timeout is active (the
    disabled hot path keeps no per-thread state)."""
    return [(lk.name, lk.rank) for lk in _held()]


def lock_ranks() -> Dict[str, int]:
    """{name: declared rank} for every lock registered in this process."""
    with _REG_GUARD:
        return {n: r for n, (r, _) in sorted(_RANKS.items())}


def _register(name: str, rank: int, reentrant: bool):
    with _REG_GUARD:
        prev = _RANKS.get(name)
        if prev is not None and prev[0] != rank:
            raise ValueError(
                f"lock {name!r} already registered with rank {prev[0]}; "
                f"a second creation site declared rank {rank} — one rank "
                f"per name (see the rank table in docs/static_analysis.md)")
        _RANKS[name] = (int(rank), bool(reentrant))


class NamedLock:
    """A ``threading.Lock``/``RLock`` with a registered name + rank.

    Context-manager and acquire/release compatible with the raw
    primitives (Condition-compatible too: ``NamedCondition`` wraps one).
    """

    __slots__ = ("name", "rank", "telemetry", "reentrant", "_lock",
                 "_t_hold", "_depth")

    def __init__(self, name: str, rank: int, *, reentrant: bool = False,
                 telemetry: bool = True):
        _register(name, rank, reentrant)
        self.name = name
        self.rank = int(rank)
        self.reentrant = bool(reentrant)
        self.telemetry = bool(telemetry)
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._t_hold = 0.0
        self._depth = 0  # reentrant recursion depth (holder-only state)

    # -- core protocol -----------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _ACTIVE:
            return self._lock.acquire(blocking, timeout)
        return self._acquire_slow(blocking, timeout)

    def release(self):
        # Bookkeeping is cleaned up unconditionally, NOT gated on the
        # CURRENT flag state: a flag toggled mid-hold must not strand a
        # held-stack entry (poisoning later inversion counts and timeout
        # reports for this thread) or leak a stale _t_hold into a bogus
        # wall-clock-sized hold_us after re-enable.  Never-activated
        # processes pay one tls getattr + two falsy checks here.
        h = getattr(_TLS, "held", None)
        if h:
            for i in range(len(h) - 1, -1, -1):
                if h[i] is self:
                    del h[i]
                    break
        if self._depth > 0:  # only ever set by reentrant slow-path holds
            self._depth -= 1
            last = self._depth == 0
        else:
            last = True
        if self._t_hold and last:
            # safe un-locked: only the holder reaches this between its
            # acquire and release
            if _TELEMETRY and self.telemetry:
                _counters(self.name)[3].inc(
                    int((time.perf_counter() - self._t_hold) * 1e6))
            self._t_hold = 0.0
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        probe = getattr(self._lock, "locked", None)
        if probe is not None:
            return probe()
        # RLock pre-3.14 has no locked(); a bare acquire(False) probe
        # would RE-ENTER when this thread is the holder and report the
        # held lock as free — check ownership first
        owned = getattr(self._lock, "_is_owned", None)
        if owned is not None and owned():
            return True
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    # -- slow path ---------------------------------------------------------
    def _acquire_slow(self, blocking, timeout, use_timeout=True) -> bool:
        tel = _TELEMETRY and self.telemetry
        if tel:
            held = _held()
            if held:
                top = max((lk.rank for lk in held if lk.name != self.name),
                          default=-1)
                if top >= self.rank:
                    # observed (never raised): the static lint owns
                    # enforcement; runtime only counts the evidence
                    _mon().counter("lock.order_inversions").inc()
        if not blocking or timeout != -1:
            # caller manages its own non-blocking/deadline semantics
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._track_acquired(tel, contended=False, t0=0.0)
            return ok
        t0 = time.perf_counter() if tel else 0.0
        contended = False
        timeout_s = _TIMEOUT_S if use_timeout else 0.0
        if tel and not self._lock.acquire(False):
            contended = True
            ok = (self._lock.acquire(True, timeout_s) if timeout_s > 0
                  else self._lock.acquire())
        elif not tel:
            ok = (self._lock.acquire(True, timeout_s) if timeout_s > 0
                  else self._lock.acquire())
        else:
            ok = True
        if not ok:
            self._raise_timeout()
        self._track_acquired(tel, contended, t0)
        return True

    def _track_acquired(self, tel, contended, t0):
        _held().append(self)
        if self.reentrant:
            self._depth += 1
        if tel:
            c = _counters(self.name)
            c[0].inc()
            if contended:
                c[1].inc()
                c[2].inc(int((time.perf_counter() - t0) * 1e6))
            if not self.reentrant or self._depth == 1:
                # a nested re-entry must not clobber the outer hold's
                # start: hold_us spans first-acquire to last-release
                self._t_hold = time.perf_counter()

    def _raise_timeout(self):
        from ..errors import LockTimeoutError

        held = [(lk.name, lk.rank) for lk in _held() if lk is not self]
        held_s = (", ".join(f"{n!r} (rank {r})" for n, r in held)
                  or "no named locks")
        raise LockTimeoutError(
            f"could not acquire lock {self.name!r} (rank {self.rank}) "
            f"within FLAGS_lock_timeout_s={_TIMEOUT_S}s; this thread "
            f"holds {held_s} — suspected deadlock or lock-order "
            f"inversion (declared order: see docs/static_analysis.md)",
            wanted=self.name, wanted_rank=self.rank, held=held,
            timeout_s=_TIMEOUT_S)

    # -- threading.Condition integration -----------------------------------
    def _release_save(self):
        self.release()

    def _acquire_restore(self, _saved):
        """Condition.wait's lock re-acquisition — EXEMPT from
        FLAGS_lock_timeout_s: the waiter holds nothing (it just released
        this very lock), so a slow reacquire is queueing behind short
        critical sections, not the deadlock class the timeout hunts; and
        raising here would propagate out of wait() with the lock UNHELD,
        making the enclosing with-block's release() raise and mask the
        diagnostic."""
        if not _ACTIVE:
            self._lock.acquire()
            return
        self._acquire_slow(True, -1, use_timeout=False)

    def _is_owned(self) -> bool:
        inner = self._lock
        owned = getattr(inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __repr__(self):
        return (f"NamedLock({self.name!r}, rank={self.rank}"
                f"{', reentrant' if self.reentrant else ''})")


class NamedCondition:
    """``threading.Condition`` over a ``NamedLock`` (non-reentrant): the
    condition's lock participates in the declared order and telemetry
    exactly like any other named lock; ``wait()`` releases/reacquires
    through the wrapper so the held-lock bookkeeping stays true."""

    __slots__ = ("_nl", "_cond")

    def __init__(self, name: str, rank: int, *, telemetry: bool = True):
        self._nl = NamedLock(name, rank, telemetry=telemetry)
        self._cond = threading.Condition(self._nl)

    @property
    def name(self) -> str:
        return self._nl.name

    @property
    def rank(self) -> int:
        return self._nl.rank

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._nl.acquire(blocking, timeout)

    def release(self):
        self._nl.release()

    def __enter__(self):
        self._cond.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cond.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __repr__(self):
        return f"NamedCondition({self.name!r}, rank={self.rank})"


def named_lock(name: str, rank: int, *, reentrant: bool = False,
               telemetry: bool = True) -> NamedLock:
    """THE way framework code creates a mutex (the concurrency lint
    rejects raw ``threading.Lock()`` in ``paddle_tpu/``).  ``rank``
    declares the lock's position in the process-wide acquisition order —
    only strictly-ascending nesting is legal."""
    return NamedLock(name, rank, reentrant=reentrant, telemetry=telemetry)


def named_rlock(name: str, rank: int, *, telemetry: bool = True) -> NamedLock:
    """Re-entrant variant: same-name re-acquisition by the holding thread
    is legal (and exempt from the rank check)."""
    return NamedLock(name, rank, reentrant=True, telemetry=telemetry)


def named_condition(name: str, rank: int, *,
                    telemetry: bool = True) -> NamedCondition:
    """A condition variable whose underlying lock is named + ranked."""
    return NamedCondition(name, rank, telemetry=telemetry)


refresh_from_flags()
