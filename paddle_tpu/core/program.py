"""Program IR: Program / Block / Operator / Variable.

Reference counterparts: `framework/framework.proto:24-188` (ProgramDesc /
BlockDesc / OpDesc / VarDesc) and `python/paddle/fluid/framework.py`
(Variable:355, Operator:963, Block:1413, Program:2752, program_guard:3749).

Design differences from the reference (TPU-first):
  * The IR is *only* a build-time artifact.  Nothing interprets it op-by-op at
    runtime; the executor lowers a whole block to one JAX/XLA computation,
    compiles it once and caches it (see core/executor.py).  So ops carry no
    kernels — just a type, slot-named inputs/outputs and attrs, mirroring
    OpDesc (framework.proto:43).
  * Serialization is JSON (`Program.to_dict`/`from_dict`) instead of protobuf;
    the shape of the data matches ProgramDesc closely so a proto codec can be
    slotted in later without touching builders.
  * Every mutation bumps `Program.version`, which keys the executor's
    compile cache — the TPU analogue of the reference's
    `use_program_cache` (executor.py:564).
"""
from __future__ import annotations

import contextlib
import copy
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import unique_name
from .dtypes import canonical_dtype


class Variable:
    """A named tensor slot inside a Block (reference: framework.py:355).

    shape uses -1 for the dynamic batch dimension; concrete shapes are bound
    at feed time and are part of the executor's compile-cache key.
    """

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        lod_level: int = 0,
        persistable: bool = False,
        stop_gradient: bool = False,
        trainable: bool = False,
        is_data: bool = False,
        initializer=None,
        regularizer=None,
        error_clip=None,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = canonical_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.trainable = trainable
        self.is_data = is_data
        self.initializer = initializer
        self.regularizer = regularizer
        self.error_clip = error_clip
        # Filled by ops/layers for parity with `Variable.op` in the reference.
        self.op: Optional["Operator"] = None

    # --- convenience used by layers -------------------------------------
    @property
    def program(self) -> "Program":
        return self.block.program

    def astype(self, dtype):
        from ..layers import tensor as tensor_layers

        return tensor_layers.cast(self, dtype)

    # Python operator sugar (reference: framework.py monkey-patches these).
    def _binary(self, other, op):
        from ..layers import math_sugar

        return math_sugar.binary(self, other, op)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        from ..layers import math_sugar

        return math_sugar.binary(other, self, "elementwise_sub")

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __matmul__(self, other):
        from ..layers import nn

        return nn.matmul(self, other)

    def __neg__(self):
        from ..layers import math_sugar

        return math_sugar.binary(self, -1.0, "elementwise_mul")

    def __repr__(self):
        return (
            f"Variable(name={self.name!r}, shape={self.shape}, dtype={self.dtype}, "
            f"persistable={self.persistable})"
        )

    __str__ = __repr__

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "trainable": self.trainable,
            "is_data": self.is_data,
        }


class Parameter(Variable):
    """A trainable persistable Variable (reference: framework.py Parameter)."""

    def __init__(self, block, name, **kw):
        kw.setdefault("persistable", True)
        kw.setdefault("trainable", True)
        super().__init__(block, name, **kw)
        self.optimize_attr = kw.get("optimize_attr", {"learning_rate": 1.0})


class Operator:
    """One op descriptor (reference: framework.py:963 / OpDesc framework.proto:43).

    inputs/outputs map slot name -> list of variable names.  attrs are
    JSON-serializable python values.  Sub-blocks (control flow) are referenced
    by block index in attrs["sub_block"].
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, List[str]]] = None,
        outputs: Optional[Dict[str, List[str]]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self) -> List[str]:
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self) -> List[str]:
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Operator({self.type}, in={ins}, out={outs})"

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": _jsonify_attrs(self.attrs),
        }


def _jsonify_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


def _dejsonify_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = np.array(v["__ndarray__"], dtype=v["dtype"])
        else:
            out[k] = v
    return out


class Block:
    """An ordered list of ops plus a var table (reference: framework.py:1413)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # --- vars ------------------------------------------------------------
    def create_var(self, name: Optional[str] = None, **kw) -> Variable:
        if name is None:
            name = unique_name.generate("tmp")
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kw)
        self.vars[name] = v
        self.program._bump()
        return v

    def create_parameter(self, name: str, shape, dtype, **kw) -> Parameter:
        p = Parameter(self, name, shape=shape, dtype=dtype, **kw)
        self.vars[name] = p
        self.program._bump()
        return p

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError(f"variable {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # --- ops -------------------------------------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        from .registry import infer_and_check  # late import: registry needs Block

        op = Operator(self, type, _normalize_io(inputs), _normalize_io(outputs), attrs)
        if _device_guard_stage is not None and "pipeline_stage" not in op.attrs:
            op.attrs["pipeline_stage"] = _device_guard_stage
        self.ops.append(op)
        infer_and_check(op, self)
        self.program._bump()
        return op

    def _insert_op(self, index: int, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, _normalize_io(inputs), _normalize_io(outputs), attrs)
        self.ops.insert(index, op)
        self.program._bump()
        return op

    def to_dict(self) -> dict:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }


def _normalize_io(io) -> Dict[str, List[str]]:
    """Accept {slot: Variable|name|list-of-either} and normalize to names."""
    if io is None:
        return {}
    out: Dict[str, List[str]] = {}
    for slot, v in io.items():
        if v is None:
            continue
        if not isinstance(v, (list, tuple)):
            v = [v]
        names = []
        for item in v:
            if isinstance(item, Variable):
                names.append(item.name)
            elif isinstance(item, str):
                names.append(item)
            else:
                raise TypeError(f"bad io entry for slot {slot!r}: {item!r}")
        out[slot] = names
    return out


class Program:
    """A list of Blocks; block 0 is global (reference: framework.py:2752)."""

    def __init__(self):
        import uuid

        self.blocks: List[Block] = [Block(self, 0)]
        # stable identity for executor compile-cache keys (id() can be reused
        # after gc; deepcopy in clone() gets a fresh one below)
        self._uuid = uuid.uuid4().hex
        self.current_block_idx = 0
        self.random_seed: Optional[int] = None
        self.version = 0
        # sharding hints attached by the parallel layer (mesh axis -> dim)
        self.sharding_hints: Dict[str, Any] = {}
        self._seed_counter = 0

    def _bump(self):
        self.version += 1

    def block(self, index: int):
        """reference Program.block(index)."""
        return self.blocks[index]

    def to_string(self, throw_on_error=False, with_details=False):
        """reference Program.to_string: the serialized program text."""
        import json

        return json.dumps(self.to_dict(), indent=2, default=str)

    @staticmethod
    def parse_from_string(s: str):
        """reference Program.parse_from_string over the JSON serde."""
        import json

        return Program.from_dict(json.loads(s))

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        self._bump()
        return b

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def all_parameters(self) -> List[Parameter]:
        return self.global_block().all_parameters()

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy the program.  for_test=True switches is_test attrs on
        (dropout becomes identity, batch_norm uses running stats) and prunes
        the backward/optimizer tail, mirroring Program.clone(for_test=True)
        in the reference (framework.py:2752 area)."""
        import uuid

        p = copy.deepcopy(self)
        p._uuid = uuid.uuid4().hex
        if for_test:
            for blk in p.blocks:
                cut = None
                for i, op in enumerate(blk.ops):
                    if op.type == "backward":
                        cut = i
                        break
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
                if cut is not None and blk.idx == 0:
                    blk.ops = blk.ops[:cut]
        p._bump()
        return p

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "random_seed": self.random_seed,
            "blocks": [b.to_dict() for b in self.blocks],
        }

    @staticmethod
    def from_dict(d: dict) -> "Program":
        p = Program()
        p.random_seed = d.get("random_seed")
        p.blocks = []
        for bd in d["blocks"]:
            b = Block(p, bd["idx"], bd.get("parent_idx", -1))
            for vd in bd["vars"]:
                v = Variable(
                    b,
                    vd["name"],
                    shape=vd["shape"],
                    dtype=vd["dtype"],
                    lod_level=vd.get("lod_level", 0),
                    persistable=vd.get("persistable", False),
                    stop_gradient=vd.get("stop_gradient", False),
                    is_data=vd.get("is_data", False),
                )
                if vd.get("trainable"):
                    v.__class__ = Parameter
                    v.trainable = True
                    v.optimize_attr = {"learning_rate": 1.0}
                b.vars[v.name] = v
            for od in bd["ops"]:
                b.ops.append(
                    Operator(b, od["type"], od["inputs"], od["outputs"], _dejsonify_attrs(od["attrs"]))
                )
            p.blocks.append(b)
        if not p.blocks:
            p.blocks = [Block(p, 0)]
        p._bump()
        return p

    def __repr__(self):
        lines = [f"Program(version={self.version})"]
        for blk in self.blocks:
            lines.append(f"  Block {blk.idx} (parent {blk.parent_idx}):")
            for op in blk.ops:
                lines.append(f"    {op}")
        return "\n".join(lines)


# --- default program / guard machinery (reference: framework.py:3749) -----

_main_program = Program()
_startup_program = Program()
_device_guard_stage: Optional[int] = None


@contextlib.contextmanager
def device_guard(device=None):
    """Reference: framework.device_guard("gpu:0") — tags appended ops with a
    pipeline stage for PipelineOptimizer to cut on.  Accepts an int stage or
    a "gpu:N"/"tpu:N" string (device kind is irrelevant on a mesh; only the
    stage index survives)."""
    global _device_guard_stage
    prev = _device_guard_stage
    if device is None:
        _device_guard_stage = None
    elif isinstance(device, int):
        _device_guard_stage = device
    else:
        tail = str(device).rsplit(":", 1)[-1]
        # "cpu" / "gpu" with no index (reference accepts these): no stage tag
        _device_guard_stage = int(tail) if tail.isdigit() else None
    try:
        yield
    finally:
        _device_guard_stage = prev


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    global _main_program, _startup_program
    old_main, old_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program = old_main
        _startup_program = old_startup


def switch_main_program(program: Program) -> Program:
    global _main_program
    old = _main_program
    _main_program = program
    return old


@contextlib.contextmanager
def name_scope(prefix=None):
    """reference framework.name_scope: prefixes generated op/var names for
    readability (debugging/graphviz); purely cosmetic here too.  Repeated
    sibling scopes dedup (encoder, encoder_1) and nesting composes
    (outer/inner); counters are NOT reset, so layers in identically-named
    scopes never collide."""
    from . import unique_name

    if prefix:
        with unique_name.name_scope_guard(prefix):
            yield
    else:
        yield
