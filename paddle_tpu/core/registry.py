"""Op registry: lowering rules from program ops to JAX.

Reference counterpart: `framework/op_registry.h:66` + `framework/op_info.cc`
(static registration of ops, kernels, grad makers).  The TPU rebuild needs no
per-device kernel table and no grad makers:

  * every op registers ONE `lower` function that emits jax.numpy / lax calls;
    XLA does the per-backend codegen the reference's CPU/CUDA/MKLDNN kernels
    did by hand;
  * gradients come from `jax.vjp` over the lowered forward segment
    (core/autodiff.py), so there is no grad-op vocabulary to register.

`lower(ctx, op, ins)` receives `ins` as {slot: [jax values]} and returns
{slot: [jax values]}.  `ctx` is a LoweringContext (core/lowering.py) giving
RNG keys, train/eval mode and mesh info.

`infer(op, block)` is the compile-time InferShape role (reference
shape_inference.h): validate input shapes/dtypes and declare outputs at
`append_op` time.  Rules are registered next to the lowerings via
`set_infer` / `core.analysis.register_rule`; `infer_and_check` classifies
any failure as a `ShapeInferenceError` carrying op/var/block provenance.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

OpLowerFn = Callable  # (ctx, op, ins) -> {slot: [values]}
InferFn = Callable  # (op, block) -> None (sets output var shapes/dtypes)
CostFn = Callable  # (op, block, env) -> (flops, traffic_bytes)


class OpDef:
    def __init__(self, type: str, lower: OpLowerFn, infer: Optional[InferFn] = None,
                 cost: Optional[CostFn] = None):
        self.type = type
        self.lower = lower
        self.infer = infer
        self.cost = cost


_REGISTRY: Dict[str, OpDef] = {}


def register_op(type: str, infer: Optional[InferFn] = None):
    """Decorator: @register_op("relu") def _relu(ctx, op, ins): ..."""

    def deco(fn: OpLowerFn):
        prev = _REGISTRY.get(type)
        d = OpDef(type, fn, infer)
        if infer is None and prev is not None and prev.infer is not None:
            d.infer = prev.infer  # re-registration keeps an attached infer
        if prev is not None and prev.cost is not None:
            d.cost = prev.cost  # re-registration keeps an attached cost rule
        _REGISTRY[type] = d
        return fn

    return deco


def set_infer(type: str, infer: InferFn):
    """Attach a build-time shape/dtype inference fn to a registered op."""
    try:
        _REGISTRY[type].infer = infer
    except KeyError:
        raise KeyError(
            f"set_infer({type!r}): op has no registered lowering"
        ) from None


def set_cost(type: str, cost: CostFn):
    """Attach a static FLOPs/bytes cost rule to a registered op (the
    resource planner's per-op model, core/resource_plan.py).  Registered
    next to the lowerings in ops/* like the `infer=` rules."""
    try:
        _REGISTRY[type].cost = cost
    except KeyError:
        raise KeyError(
            f"set_cost({type!r}): op has no registered lowering"
        ) from None


def suggest_ops(type: str, n: int = 3) -> List[str]:
    """Nearest-matching registered op types for an unknown-op error."""
    import difflib

    return difflib.get_close_matches(type, sorted(_REGISTRY), n=n)


def get_op_def(type: str, op=None, block=None) -> OpDef:
    """Look up an op's definition.  On a miss, the error names the op's
    block context (when given) and suggests nearest-matching registered
    types instead of dumping the whole registry."""
    try:
        return _REGISTRY[type]
    except KeyError:
        close = suggest_ops(type)
        hint = (f"; did you mean: {', '.join(close)}?" if close
                else "; see paddle_tpu.core.registry.registered_ops() for "
                     "the full list")
        where = ""
        if block is not None:
            idx = None
            if op is not None:
                try:
                    idx = block.ops.index(op)
                except ValueError:
                    idx = None
            where = (f" (block {block.idx}"
                     + (f", op #{idx}" if idx is not None else "")
                     + ")")
        raise NotImplementedError(
            f"op {type!r}{where} has no registered lowering{hint} "
            f"({len(_REGISTRY)} ops registered)"
        ) from None


def get_op_def_or_none(type: str) -> Optional[OpDef]:
    return _REGISTRY.get(type)


def has_op(type: str) -> bool:
    return type in _REGISTRY


def registered_ops():
    return sorted(_REGISTRY)


def infer_and_check(op, block):
    """Run build-time shape/dtype inference if the op registered one.

    Mirrors the reference's compile-time InferShape (shape_inference.h); ops
    the framework appends (feed/fetch/backward) are exempt.  Failures are
    classified `ShapeInferenceError`s (core/analysis.py) so `append_op`
    raises with op/var/block provenance instead of the program dying later
    inside JAX tracing."""
    d = _REGISTRY.get(op.type)
    if d is None or d.infer is None:
        return
    from ..flags import flag as _flag

    if _flag("FLAGS_verify_program") in ("", "off"):
        return  # 'off' trusts the builder: the escape hatch for a program
        # an (over-strict or wrong) infer rule would reject at build time
    from ..monitor import MONITOR as _MON
    from .analysis import ShapeInferenceError, StaticAnalysisError, _op_index

    try:
        d.infer(op, block)
        _MON.counter("analysis.infer_checks").inc()
    except StaticAnalysisError:
        _MON.counter("analysis.infer_failures").inc()
        raise
    except Exception as e:
        _MON.counter("analysis.infer_failures").inc()
        raise ShapeInferenceError(
            f"shape/dtype inference crashed for op #{_op_index(block, op)} "
            f"({op.type!r}) in block {block.idx}: {e!r}"
        ) from e
