"""Op registry: lowering rules from program ops to JAX.

Reference counterpart: `framework/op_registry.h:66` + `framework/op_info.cc`
(static registration of ops, kernels, grad makers).  The TPU rebuild needs no
per-device kernel table and no grad makers:

  * every op registers ONE `lower` function that emits jax.numpy / lax calls;
    XLA does the per-backend codegen the reference's CPU/CUDA/MKLDNN kernels
    did by hand;
  * gradients come from `jax.vjp` over the lowered forward segment
    (core/autodiff.py), so there is no grad-op vocabulary to register.

`lower(ctx, op, ins)` receives `ins` as {slot: [jax values]} and returns
{slot: [jax values]}.  `ctx` is a LoweringContext (core/lowering.py) giving
RNG keys, train/eval mode and mesh info.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

OpLowerFn = Callable  # (ctx, op, ins) -> {slot: [values]}
InferFn = Callable  # (op, block) -> None (sets output var shapes/dtypes)


class OpDef:
    def __init__(self, type: str, lower: OpLowerFn, infer: Optional[InferFn] = None):
        self.type = type
        self.lower = lower
        self.infer = infer


_REGISTRY: Dict[str, OpDef] = {}


def register_op(type: str, infer: Optional[InferFn] = None):
    """Decorator: @register_op("relu") def _relu(ctx, op, ins): ..."""

    def deco(fn: OpLowerFn):
        _REGISTRY[type] = OpDef(type, fn, infer)
        return fn

    return deco


def get_op_def(type: str) -> OpDef:
    try:
        return _REGISTRY[type]
    except KeyError:
        raise NotImplementedError(
            f"op {type!r} has no registered lowering; registered ops: "
            f"{sorted(_REGISTRY)}"
        ) from None


def has_op(type: str) -> bool:
    return type in _REGISTRY


def registered_ops():
    return sorted(_REGISTRY)


def infer_and_check(op, block):
    """Run build-time shape/dtype inference if the op registered one.

    Mirrors the reference's compile-time InferShape (shape_inference.h); ops
    the framework appends (feed/fetch/backward) are exempt.
    """
    d = _REGISTRY.get(op.type)
    if d is not None and d.infer is not None:
        d.infer(op, block)
