"""Executor: compile-and-run of whole programs.

Reference counterparts: `python/paddle/fluid/executor.py` (Executor:292,
run:564) and `framework/executor.cc:150` (per-op interpreter).

TPU-first redesign: `run()` does NOT interpret ops.  It lowers the program's
global block to ONE jax function (forward + vjp backward + optimizer update),
jit-compiles it, caches the executable keyed by (program version, feed
signature, state signature, fetch names) — the role the reference's
`use_program_cache` played — and executes it.  Persistent state (parameters,
optimizer accumulators, RNG key) lives in a Scope as device arrays and is
donated to the executable each step, so parameter updates are in-place in HBM.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..dist_resilience import guard_blocking as _guard_blocking
from ..monitor import MONITOR as _MON
from . import locks
from .dtypes import as_np_dtype
from .lowering import LoweringContext, run_block_with_backward
from .program import Program, Variable, default_main_program
from .scope import RNG_STATE_VAR, Scope, global_scope


class Place:
    pass


class TPUPlace(Place):
    """Device handle (reference: platform/place.h CUDAPlace:37)."""

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"

    def jax_device(self):
        # local_devices: under multi-process, jax.devices() lists the global
        # topology but only local ones can receive single-device work
        devs = [d for d in jax.local_devices() if d.platform != "cpu"] or jax.local_devices()
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    def __init__(self):
        self.device_id = 0

    def __repr__(self):
        return "CPUPlace()"

    def jax_device(self):
        try:
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            return jax.local_devices()[0]


# CUDAPlace alias keeps reference-era scripts importable; it is a TPU device.
CUDAPlace = TPUPlace


class CUDAPinnedPlace(Place):
    """reference CUDAPinnedPlace: host-pinned staging memory.  PJRT manages
    transfer staging itself, so this is the host (CPU) place."""

    def __init__(self):
        self.device_id = 0

    def jax_device(self):
        return CPUPlace().jax_device()


def cpu_places(device_count=None):
    """reference fluid.cpu_places."""
    import os

    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """reference fluid.cuda_places: accelerator places (TPU chips here)."""
    if device_ids is None:
        n = len([d for d in jax.local_devices() if d.platform != "cpu"]) or 1
        device_ids = range(n)
    return [TPUPlace(i) for i in device_ids]


def cuda_pinned_places(device_count=None):
    import os

    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CUDAPinnedPlace() for _ in range(n)]


def _runnable_ops(block):
    return [op for op in block.ops if op.type not in ("feed", "fetch")]


def _lowering_flags():
    """Process-global lowering options that change generated code; they must
    participate in the compile-cache key or toggling them would silently
    reuse stale executables."""
    from ..flags import flag as _flagv
    from ..ops import nn_ops

    return ("nhwc", nn_ops._NHWC_LOWERING, "bn1p", nn_ops._BN_SINGLE_PASS,
            "bnbf16", nn_ops._BN_BF16_COMPUTE,
            "bnfused", nn_ops._BN_STATS_FUSED_PASS,
            "bnfdef", nn_ops._BN_BF16_FUSED_DEFAULT,
            "bnbar", nn_ops._BN_UNFUSE_CONV,
            "pallas", bool(_flagv("FLAGS_use_pallas")))


class _CompiledStep:
    """One jitted executable for (program, feed sig, fetch names, state sig)."""

    def __init__(self, program: Program, feed_names: Sequence[str], fetch_names: Sequence[str], scope: Scope,
                 mesh=None, batch_axis: str = "dp", feed_shapes: Optional[Dict[str, tuple]] = None,
                 n_steps: int = 1, remat: bool = False, platform: Optional[str] = None,
                 local_sgd: bool = False, grad_overlap=None):
        self.mesh = mesh
        self.platform = platform
        self.batch_axis = batch_axis
        self.n_steps = n_steps
        self.remat = remat
        self.multiprocess = mesh is not None and any(
            d.process_index != jax.process_index() for d in mesh.devices.flat
        )
        # AOT executable state: trace/lower and XLA-compile are split out of
        # dispatch (jax.jit's .trace().lower().compile()) so the monitor can
        # time each phase; re-built on state-aval change like jit's retrace.
        # _exec_by_sig keeps previously built executables so programs whose
        # state avals alternate don't recompile on every flip (the multi-
        # entry cache jit provided); the signature is only computed on the
        # miss path, never in steady state.
        self.program_uuid = program._uuid[:8]
        # cross-rank correlation key (ISSUE 8): every rank compiling this
        # (program, mesh) pair derives the same digest, so
        # tools/trace_merge.py can line up "the same collective-bearing
        # step" across per-rank telemetry streams by (csig, step number).
        # RANK-INVARIANT by construction: built from the program's
        # structure (op types + arg names — identical when every rank
        # built the same program, which the collective-order lint already
        # demands), its static collective_signature, and the mesh shape —
        # never from per-process identities like program._uuid.  None
        # off-mesh: nothing to correlate.
        self.csig = None
        if mesh is not None:
            try:
                import hashlib

                from .analysis import collective_signature

                structure = tuple(
                    (op.type, tuple(op.input_arg_names),
                     tuple(op.output_arg_names))
                    for blk in program.blocks for op in blk.ops)
                self.csig = hashlib.sha1(
                    repr((structure, collective_signature(program),
                          tuple(sorted(dict(mesh.shape).items())))).encode()
                ).hexdigest()[:8]
            except Exception:
                self.csig = None
        self._exec = None
        self._exec_by_sig: Dict[tuple, object] = {}
        # serving clones share _CompiledStep instances across threads: two
        # threads cold-starting the same signature must build ONE
        # executable (a double trace+compile would double-count the
        # recompile gate and waste the compile lane)
        self._build_lock = locks.named_lock("executor.build", rank=26)
        self.last_lower_s = 0.0
        self.last_compile_s = 0.0
        self.last_recompiled = False
        feed_shapes = feed_shapes or {}
        block = program.global_block()
        ops = _runnable_ops(block)

        persistable = {
            v.name for v in program.list_vars() if v.persistable
        }
        ops = self._prune(ops, fetch_names, persistable)
        read_names = set()
        written = []
        written_set = set()
        for op in ops:
            # _effective_io folds in sub-block reads/writes (while / cond /
            # dynamic_rnn bodies read parameters the top-level op doesn't list)
            reads, outs = self._effective_io(op)
            read_names.update(reads)
            if op.type == "backward":
                read_names.update(op.attrs.get("param_names", []))
            for n in outs:
                if n in persistable and n not in written_set:
                    written_set.add(n)
                    written.append(n)
        # grads of params: backward writes grad vars which may be persistable? no.
        needed = (read_names | set(fetch_names)) & persistable
        self.state_in_names = sorted(n for n in needed if scope.has_var(n))
        self.written_names = written
        self.fetch_names = list(fetch_names)
        self.feed_names = list(feed_names)

        # Donate only buffers the step overwrites (params/accumulators under
        # an optimizer); read-only state is passed undonated.
        self.rw_names = [n for n in self.state_in_names if n in written_set]
        self.ro_names = [n for n in self.state_in_names if n not in written_set]

        # Backward-overlapped dp gradient all-reduce (CompiledProgram.
        # with_grad_overlap): the step runs inside a manual shard_map region
        # and grads are bucket-psum'd via the LoweringContext hook.
        self._grad_sync = None
        if grad_overlap is not None:
            overlap_mode, bucket_bytes = grad_overlap
            if mesh is None or not dict(mesh.shape).get(batch_axis):
                raise ValueError(
                    "with_grad_overlap needs a mesh with a batch axis "
                    "(CompiledProgram.with_data_parallel / with_mesh first)")
            if program.sharding_hints:
                raise NotImplementedError(
                    "with_grad_overlap is a pure-dp path (replicated "
                    "state); programs with sharding_hints keep the GSPMD "
                    "collectives")
            from ..parallel.distributed import make_grad_sync

            self._grad_sync = make_grad_sync(batch_axis, bucket_bytes,
                                             mode=overlap_mode)

        def step(state_rw: Dict[str, jnp.ndarray], state_ro: Dict[str, jnp.ndarray],
                 feeds: Dict[str, jnp.ndarray], key):
            ctx = LoweringContext(key, mesh=mesh, platform=self.platform)
            ctx.remat = self.remat
            ctx.grad_sync = self._grad_sync
            ctx.fetch_names = tuple(self.fetch_names)
            env = dict(state_ro)
            env.update(state_rw)
            env.update(feeds)
            env = run_block_with_backward(ctx, ops, env)
            new_state = {n: env[n] for n in written if n in env}
            fetches = [env[n] for n in self.fetch_names]
            return fetches, new_state, ctx.key

        # dp geometry shared by every feed-sharding consumer below (LocalSGD
        # and overlap shard_map in_specs, jit-level in_shardings).
        # feed_shapes are the caller's LOCAL per-process shapes; when the
        # batch axis spans processes each feed's global batch is
        # local * dp_procs, so divisibility checks must use the per-process
        # dp share, not the global dp size.
        if mesh is not None:
            n_dp = dict(mesh.shape).get(batch_axis, 0)  # 0: no data axis (e.g. pure pp mesh)
            dp_spans = False
            dp_procs = 1
            if self.multiprocess and n_dp:
                ax = list(mesh.axis_names).index(batch_axis)
                line = np.moveaxis(mesh.devices, ax, 0).reshape(n_dp, -1)[:, 0]
                procs = {d.process_index for d in line}
                dp_spans = len(procs) > 1
                dp_procs = max(len(procs), 1)
            n_dp_local = max(n_dp // dp_procs, 1) if dp_spans else n_dp

            def _feed_pspec(n):
                # CONTRACT (cross-process dp): every feed with a batch dim
                # is this process's slice of the global batch, sharded over
                # the dp axis exactly when the local batch divides this
                # process's dp share; replicated non-scalar data must be
                # passed as a pre-placed jax.Array.  The ONE copy of this
                # rule feeds the LocalSGD and overlap shard_map in_specs
                # and the jit in_shardings — if two of them disagreed,
                # shard_map would all-gather the batch and every worker
                # would compute the full global batch (dp silently gone).
                from jax.sharding import PartitionSpec as P

                shape = feed_shapes.get(n, ())
                bdim = 1 if n_steps > 1 else 0  # steps>1: axis 0 is scan
                if (n_dp and len(shape) > bdim
                        and shape[bdim] % n_dp_local == 0):
                    return P(*([None] * bdim + [batch_axis]))
                if dp_spans and len(shape) > bdim and shape[bdim] > 1:
                    # replicating per-process data that differs across
                    # processes silently breaks sync-SGD; refuse instead
                    raise ValueError(
                        f"multiprocess feed {n!r}: local batch "
                        f"{shape[bdim]} is not divisible by this process's "
                        f"dp share ({n_dp_local}); pad the local batch or "
                        f"adjust the mesh")
                return P()

        if n_steps > 1:
            # Multi-step dispatch: lax.scan the whole train step over feeds
            # stacked on a leading [n_steps] axis.  One host->device dispatch
            # drives K optimizer steps — the TPU answer to the reference's
            # dataset-driven trainer hot loop (`hogwild_worker.cc:137`:
            # `for op in ops: op->Run()` per batch, no Python between steps).
            # Requires every written persistable to round-trip through the
            # carry, i.e. written ⊆ read state (true for params/accumulators).
            missing = [n for n in written if n not in set(self.rw_names)]
            if missing:
                raise ValueError(
                    f"steps>1 needs write-back state to be read by the program "
                    f"too; write-only persistables: {missing}"
                )
            inner = step

            if local_sgd:
                # LocalSGD round (reference transpiler/collective.py:249
                # LocalSGD: snapshot + allreduce param deltas every k steps).
                # TPU-native: each dp worker runs the k scanned steps on ITS
                # OWN diverging copy of the state inside a shard_map — no
                # collective between steps — then one pmean re-syncs.  One
                # dispatch = one round; the scope's single logical copy means
                # optimizer accumulators are averaged at the sync too (the
                # reference keeps them worker-local; recorded deviation).
                if mesh is None or not dict(mesh.shape).get(batch_axis):
                    raise ValueError(
                        "local_sgd needs a mesh with a batch axis "
                        "(CompiledProgram.with_local_sgd on a dp mesh)")
                if self.multiprocess:
                    # the shard_map in_specs below assume single-controller
                    # global batches; per-process slice assembly is not wired
                    raise NotImplementedError(
                        "with_local_sgd on a multi-process mesh is not "
                        "supported yet; use a single-controller dp mesh")
                from jax.sharding import PartitionSpec as P

                ls_in_feeds = {n: _feed_pspec(n) for n in self.feed_names}
                rw_repl = {n: P() for n in self.rw_names}
                ro_repl = {n: P() for n in self.ro_names}
                out_state_spec = {n: P() for n in written}

                def worker(state_rw, state_ro, feeds, key):
                    wk = jax.random.fold_in(key, jax.lax.axis_index(batch_axis))

                    def body(carry, feed_t):
                        srw, k = carry
                        fetches_t, new_state, k2 = inner(srw, state_ro, feed_t, k)
                        return (new_state, k2), fetches_t

                    (srw, _), stacked = jax.lax.scan(body, (state_rw, wk), feeds)
                    srw = jax.tree_util.tree_map(
                        lambda a: jax.lax.pmean(a, batch_axis), srw)
                    # fetch semantics under LocalSGD: the dp-MEAN of each
                    # worker's value (right for scalar losses/metrics; for
                    # per-sample outputs run a separate eval dispatch)
                    stacked = jax.tree_util.tree_map(
                        lambda a: jax.lax.pmean(a, batch_axis), stacked)
                    return stacked, srw

                from .jax_compat import shard_map as _shard_map

                smapped = _shard_map(
                    worker, mesh=mesh,
                    in_specs=(rw_repl, ro_repl, ls_in_feeds, P()),
                    out_specs=([P()] * len(self.fetch_names), out_state_spec),
                    check_vma=False,
                )

                def step(state_rw, state_ro, feeds, key):
                    stacked, srw = smapped(state_rw, state_ro, feeds, key)
                    return stacked, srw, jax.random.fold_in(key, n_steps)
            else:
                def step(state_rw, state_ro, feeds, key):
                    def body(carry, feed_t):
                        srw, k = carry
                        fetches_t, new_state, k2 = inner(srw, state_ro, feed_t, k)
                        return (new_state, k2), fetches_t

                    (srw, key2), stacked = jax.lax.scan(body, (state_rw, key), feeds)
                    return stacked, srw, key2

        if self._grad_sync is not None:
            # Manual dp region around the (possibly scanned) step: each dp
            # worker traces the program over ITS batch shard; the grad_sync
            # hook mean-reduces gradients in buckets inside the backward, so
            # parameter updates are identical across workers and the state
            # stays replicated.  DDP semantics: dropout masks and BN batch
            # stats are per-shard (each worker folds the step key with its
            # dp index); fetches come back as the dp-mean (exact for the
            # scalar losses/metrics training fetches).
            from jax.sharding import PartitionSpec as P

            from .jax_compat import shard_map as _shard_map

            ov_in_feeds = {n: _feed_pspec(n) for n in self.feed_names}
            rw_repl = {n: P() for n in self.rw_names}
            ro_repl = {n: P() for n in self.ro_names}
            out_state_spec = {n: P() for n in written}
            inner_step = step
            n_fetch = len(self.fetch_names)
            # Written state whose update is NOT grad-derived needs its own
            # sync: each worker folds ITS shard's statistics, so without
            # one the P() out_spec would claim replication over genuinely
            # divergent per-device buffers (rank-divergent checkpoints,
            # undefined eval stats).  Two classes, two reductions:
            #   - BN running mean/var: dp-MEAN — exact for the running
            #     mean, the standard shard-mean approximation for the
            #     running variance; normalization itself stays per-shard
            #     (DDP semantics).
            #   - additive accumulators (auc StatPos/StatNeg histograms):
            #     delta-PSUM — new = old + psum(new - old), so the global
            #     histogram counts every shard's samples exactly (integer
            #     math, bit-identical across serial/bucketed arms).
            bn_stat_names = set()
            acc_stat_names = set()
            # walk every block, not just the compiled op list — a BN inside
            # a while/conditional sub-block still writes persistable stats
            # into `written` and needs the same sync
            for blk in program.blocks:
                for op_ in blk.ops:
                    if (op_.type in ("batch_norm", "sync_batch_norm")
                            and not op_.attrs.get("is_test")
                            and not op_.attrs.get("use_global_stats")):
                        for slot in ("MeanOut", "VarianceOut"):
                            bn_stat_names.update(op_.outputs.get(slot, ()))
                    elif op_.type == "auc":
                        for slot in ("StatPosOut", "StatNegOut"):
                            acc_stat_names.update(op_.outputs.get(slot, ()))
            bn_stat_names &= set(written)
            acc_stat_names &= set(written)

            def worker(state_rw, state_ro, feeds, key):
                wk = jax.random.fold_in(key, jax.lax.axis_index(batch_axis))
                fetches, new_state, _ = inner_step(state_rw, state_ro, feeds, wk)
                # the dp-mean below is only meaningful for scalar losses/
                # metrics (per step); a per-sample fetch would come back as
                # the element-wise average of DIFFERENT samples across
                # shards at 1/n_dp the batch — garbage with no error.
                # Refuse at trace time instead.  (A fetch whose PER-SHARD
                # size is 1 is indistinguishable from a scalar metric here
                # and passes — shapes are shard-local inside shard_map.)
                for fname, f in zip(self.fetch_names, fetches):
                    if getattr(f, "size", 1) > max(n_steps, 1):
                        raise ValueError(
                            f"with_grad_overlap: fetch {fname!r} has shape "
                            f"{f.shape} — overlap fetches are dp-MEANed "
                            f"across workers, which is only exact for "
                            f"scalar losses/metrics; fetch a reduced "
                            f"scalar, or run evaluation through a program "
                            f"compiled without grad overlap")
                fetches = jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, batch_axis), fetches)
                if bn_stat_names or acc_stat_names:
                    def _sync_stat(n, v):
                        if n in bn_stat_names:
                            return jax.lax.pmean(v, batch_axis)
                        if n in acc_stat_names:
                            # additive accumulator: every shard starts from
                            # the same replicated base and adds its shard's
                            # delta — psum the delta, not the state, or the
                            # base would be counted n_dp times
                            return state_rw[n] + jax.lax.psum(
                                v - state_rw[n], batch_axis)
                        return v
                    new_state = {n: _sync_stat(n, v)
                                 for n, v in new_state.items()}
                return fetches, new_state

            smapped = _shard_map(
                worker, mesh=mesh,
                in_specs=(rw_repl, ro_repl, ov_in_feeds, P()),
                out_specs=([P()] * n_fetch, out_state_spec),
                check_vma=False,
            )

            def step(state_rw, state_ro, feeds, key):
                fetches, new_state = smapped(state_rw, state_ro, feeds, key)
                return fetches, new_state, jax.random.fold_in(key, max(n_steps, 1))

        if mesh is None:
            self.jfn = jax.jit(step, donate_argnums=(0,))
            self.feed_specs = None
        else:
            # SPMD: feeds batch-sharded on dim 0, state placed per program
            # sharding hints (default replicated) — GSPMD inserts the
            # gradient all-reduces the reference emitted as NCCL op handles.
            from jax.sharding import NamedSharding, PartitionSpec as P

            hints = dict(program.sharding_hints)

            def state_spec(n):
                return NamedSharding(mesh, P(*hints[n]) if n in hints else P())

            repl = NamedSharding(mesh, P())

            def feed_spec(n):
                # the dp feed-sharding contract lives in _feed_pspec (shared
                # with the overlap shard_map in_specs); this just places it
                return NamedSharding(mesh, _feed_pspec(n))

            rw_specs = {n: state_spec(n) for n in self.rw_names}
            ro_specs = {n: state_spec(n) for n in self.ro_names}
            feed_specs = {n: feed_spec(n) for n in self.feed_names}
            self.feed_specs = feed_specs
            self.state_specs = {**rw_specs, **ro_specs}
            self.key_spec = repl
            out_specs = (
                [repl] * len(self.fetch_names),
                {n: state_spec(n) for n in written},
                repl,
            )
            self.jfn = jax.jit(
                step,
                donate_argnums=(0,),
                in_shardings=(rw_specs, ro_specs, feed_specs, repl),
                out_shardings=out_specs,
            )

    @staticmethod
    def _effective_io(op):
        """(reads, writes) including sub-block effects for control flow."""
        reads = list(op.input_arg_names)
        writes = list(op.output_arg_names)
        if op.type in ("while", "conditional_block", "dynamic_rnn"):
            idx = op.attrs.get("sub_block")
            if idx is not None:
                sub = op.block.program.blocks[idx]
                for sop in sub.ops:
                    r, w = _CompiledStep._effective_io(sop)
                    reads.extend(r)
                    writes.extend(w)
        return reads, writes

    @staticmethod
    def _prune(ops, fetch_names, persistable):
        """Fetch-driven dead-op elimination (the reference prunes programs to
        feed/fetch targets at io.py save_inference_model:915; here it runs on
        every compile so eval programs don't demand training-only feeds).
        Ops are kept if they (transitively) contribute to a fetch or write a
        persistable var.  Control-flow ops count their sub-block reads and
        writes."""
        needed = set(fetch_names)
        kept = []
        for op in reversed(ops):
            reads, outs = _CompiledStep._effective_io(op)
            writes_state = any(o in persistable for o in outs)
            if writes_state or any(o in needed for o in outs):
                kept.append(op)
                needed.update(reads)
                if op.type == "backward":
                    needed.add(op.attrs["loss_name"])
                    needed.update(op.attrs.get("param_names", []))
        kept.reverse()
        return kept

    def _place(self, v, spec):
        """Host/local array -> mesh placement.  Multi-process meshes can't
        jax.device_put a local array onto non-addressable devices; each
        process instead materializes its own shards from the (replicated)
        host value via make_array_from_callback."""
        if self.multiprocess:
            host = np.asarray(v)
            return jax.make_array_from_callback(host.shape, spec, lambda idx: host[idx])
        return jax.device_put(v, spec)

    @staticmethod
    def _state_sig(state_rw, state_ro):
        return (
            tuple((n, v.shape, str(v.dtype)) for n, v in sorted(state_rw.items())),
            tuple((n, v.shape, str(v.dtype)) for n, v in sorted(state_ro.items())),
        )

    def _dispatch(self, state_rw, state_ro, feeds, key):
        """Run the step through the AOT executable, building it on first
        use (and after a state-aval change) with the block->jaxpr lowering
        and the XLA compile timed as separate monitor spans."""
        self.last_recompiled = False
        exec_ = self._exec
        if exec_ is not None:
            try:
                return exec_(state_rw, state_ro, feeds, key)
            except TypeError:
                # state avals changed (dtype promotion, resharding): the
                # aval check fires before execution, so donated buffers are
                # untouched.  Try an executable built for this signature
                # before recompiling (jit's multi-entry cache role).
                cached = self._exec_by_sig.get(self._state_sig(state_rw, state_ro))
                if cached is not None and cached is not exec_:
                    try:
                        out = cached(state_rw, state_ro, feeds, key)
                        self._exec = cached
                        return out
                    except TypeError:
                        pass
                self._exec = None
        with self._build_lock:  # lock-ok: one XLA trace+compile per executable signature IS the lock's purpose; a hit path never reaches here and the cache lock stays free throughout
            # a concurrent thread (serving clones share this step) may
            # have built the executable while we waited for the lock:
            # serve from its entry instead of compiling a duplicate
            sig = self._state_sig(state_rw, state_ro)
            cached = self._exec_by_sig.get(sig)
            if cached is not None:
                try:
                    out = cached(state_rw, state_ro, feeds, key)
                    self._exec = cached
                    return out
                except TypeError:
                    pass
            t0 = time.perf_counter()
            lowered = self.jfn.trace(state_rw, state_ro, feeds, key).lower()
            t1 = time.perf_counter()
            built = lowered.compile()
            t2 = time.perf_counter()
            self._exec = built
            self._exec_by_sig[sig] = built
            if len(self._exec_by_sig) > 8:
                self._exec_by_sig.pop(next(iter(self._exec_by_sig)))
            self.last_lower_s = t1 - t0
            self.last_compile_s = t2 - t1
            self.last_recompiled = True
        _MON.observe("executor.lower", self.last_lower_s, program=self.program_uuid)
        _MON.observe("executor.compile", self.last_compile_s, program=self.program_uuid)
        _MON.counter("executor.recompile").inc()
        return built(state_rw, state_ro, feeds, key)

    def __call__(self, scope: Scope, feeds: Dict[str, jnp.ndarray], key):
        if self.mesh is not None:
            # Reshard state committed elsewhere (e.g. by a single-device
            # startup run) onto the mesh layout the step expects.
            for n, spec in self.state_specs.items():
                v = scope.find_var(n)
                if getattr(v, "sharding", None) != spec:
                    scope.set_var(n, self._place(v, spec))
            if getattr(key, "sharding", None) != self.key_spec:
                key = self._place(key, self.key_spec)
        state_rw = {n: scope.find_var(n) for n in self.rw_names}
        state_ro = {n: scope.find_var(n) for n in self.ro_names}
        fetches, new_state, new_key = self._dispatch(state_rw, state_ro, feeds, key)
        for n, v in new_state.items():
            scope.set_var(n, v)
        return fetches, new_key


class _PendingFetches:
    """Shared state behind the FetchHandles of one `run_async` dispatch.

    Holds the still-in-flight output `jax.Array`s (plus the new RNG key, so
    `wait()` exerts backpressure even for fetch-less programs), the deferred
    host-eval plan, and the deferred NaN/Inf check.  Resolution happens at
    most once; an error raised during resolution is sticky so every handle
    of the dispatch reports the same failure."""

    __slots__ = ("fetch_names", "fetches", "key", "host_plan", "feed",
                 "scope", "program_u8", "_np", "_exc", "_done")

    def __init__(self, fetch_names, fetches, key, host_plan, feed, scope,
                 program_u8):
        self.fetch_names = list(fetch_names)
        self.fetches = list(fetches)
        self.key = key
        self.host_plan = host_plan
        self.feed = feed
        self.scope = scope
        self.program_u8 = program_u8
        self._np = None
        self._exc = None
        self._done = False

    @property
    def want_names(self):
        return self.host_plan["want"] if self.host_plan is not None else self.fetch_names

    def wait(self):
        """Block until the dispatched step has executed on the device —
        no device->host copy, no host eval.  The bounded-depth knob:
        train_loop calls this on non-logging steps.  Routed through the
        collective watchdog: on a cross-process mesh this wait sits inside
        the step's allreduce, which never completes once a peer is dead."""
        def _block():
            jax.block_until_ready(self.fetches)
            if self.key is not None:
                jax.block_until_ready(self.key)

        _guard_blocking(_block, what="executor.wait")

    def ready(self) -> bool:
        """Non-blocking readiness probe (best effort: falls back to True
        when the array type predates `is_ready`)."""
        outs = self.fetches if self.fetches else ([self.key] if self.key is not None else [])
        for a in outs:
            probe = getattr(a, "is_ready", None)
            if callable(probe) and not probe():
                return False
        return True

    def resolve(self):
        """Materialize fetches to numpy (first call only), finishing the
        deferred host-eval pass and the NaN/Inf check.  In-flight errors —
        a poisoned value caught by FLAGS_check_nan_inf, an XLA runtime
        failure surfacing at the blocking copy — raise HERE, not at
        dispatch; the scope already holds the step's output buffers, so a
        resolution failure does not corrupt persistent state."""
        if self._done:
            if self._exc is not None:
                raise self._exc
            return self._np
        mon_on = _MON.enabled
        if mon_on:
            t0 = time.perf_counter()
        try:
            if self.host_plan is not None:
                with _MON.span("executor.host_eval"):
                    vals = Executor._finish_host_eval(
                        self.host_plan, self.feed, self.fetches, self.scope)
                names = self.host_plan["want"]
            else:
                vals, names = self.fetches, self.fetch_names
            # the device->host copy (the NaN guard's np.asarray included)
            # is where an in-flight collective's block manifests;
            # watchdog-guarded so a dead peer raises (classified below)
            # instead of hanging the resolver
            def _materialize():
                Executor._check_nan_inf(names, vals)
                return [np.asarray(v) for v in vals]

            self._np = _guard_blocking(_materialize, what="executor.resolve")
        except BaseException as e:
            # route the in-flight failure through the taxonomy
            # (paddle_tpu/errors.py): an XLA RESOURCE_EXHAUSTED /
            # UNAVAILABLE surfacing at the blocking copy becomes a
            # TransientDeviceError the resilient loop can retry; anything
            # unmapped stays itself.  The classified error is the sticky
            # one — every handle of the dispatch reports the same failure.
            from ..errors import classify

            ce = classify(e)
            self._exc = ce
            if ce is e:
                raise
            raise ce from e
        finally:
            # resolution is one-shot either way: drop the device buffers,
            # the staged feed, and the key so retained handles don't pin a
            # whole batch (+ outputs) in memory past their numpy copies
            self._done = True
            self.fetches = []
            self.feed = None
            self.host_plan = None
            self.key = None
        if mon_on:
            _MON.observe("executor.fetch", time.perf_counter() - t0,
                         program=self.program_u8)
        return self._np


class FetchHandle:
    """Lazy fetch result from `Executor.run_async`.

    Wraps one output of a still-in-flight dispatch: JAX's async dispatch
    keeps the device busy while Python runs ahead, and the device->host
    copy (plus deferred host-eval / NaN check) happens only on first
    access — `numpy()`, `np.asarray(handle)`, or `float(handle)`."""

    __slots__ = ("_pending", "_idx", "name")

    def __init__(self, pending: _PendingFetches, idx: int, name: str):
        self._pending = pending
        self._idx = idx
        self.name = name

    def numpy(self) -> np.ndarray:
        return self._pending.resolve()[self._idx]

    def wait(self):
        """Block until device execution finished, WITHOUT copying to host."""
        self._pending.wait()
        return self

    # jax-style alias so generic `jax.block_until_ready`-ish call sites work
    def block_until_ready(self):
        return self.wait()

    def is_ready(self) -> bool:
        return self._pending._done or self._pending.ready()

    @property
    def has_deferred_host_work(self) -> bool:
        """True when skipping resolution would skip SIDE EFFECTS, not just
        the host copy: deferred host-eval ops write metric accumulators
        back to the scope.  train_loop resolves such steps even when it
        wouldn't log them."""
        return self._pending.host_plan is not None

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __float__(self):
        return float(np.asarray(self.numpy()).reshape(-1)[0])

    def __repr__(self):
        state = "resolved" if self._pending._done else "in-flight"
        return f"FetchHandle({self.name!r}, {state})"


class Executor:
    """Reference: executor.py:292.  `run` signature kept source-compatible."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place if place is not None else TPUPlace(0)
        self._cache: Dict[tuple, _CompiledStep] = {}
        # compile-cache bookkeeping lock: the LRU pop/re-insert pair and
        # the miss-path build/insert must be atomic — two serving threads
        # racing the same key would otherwise each count a miss and build
        # a duplicate _CompiledStep (the serving cache-share contract is
        # one compiled entry per (program, bucket shape) signature)
        self._cache_lock = locks.named_lock("executor.cache", rank=24)
        self._host_eval_cache: Dict[tuple, Program] = {}

    def close(self):
        self._cache.clear()
        self._host_eval_cache.clear()

    # -- fetch-time host evaluation (callback-less platforms) -------------
    # Reference context: chunk_eval_op.cc / detection_map_op.cc /
    # py_func_op.cc run in-process on whatever device the program uses; on
    # the axon tunnel (no host send/recv) the equivalent is: run the device
    # program WITHOUT these sink ops, fetch their inputs, evaluate on CPU.
    _HOST_EVAL_TYPES = ("chunk_eval", "detection_map", "py_func")

    def _split_host_eval(self, program, fetch_names, feed):
        from ..ops.common import _platform_lacks_callbacks

        if not _platform_lacks_callbacks(self.place.jax_device().platform):
            return program, fetch_names, None
        block = program.global_block()
        cand = [i for i, o in enumerate(block.ops)
                if o.type in self._HOST_EVAL_TYPES]
        if not cand:
            return program, fetch_names, None
        cand_set = set(cand)
        consumed = set()
        for i, o in enumerate(block.ops):
            # feed/fetch ops (saved inference programs embed them) are
            # plumbing, not device consumers — a fetch targeting a sink's
            # output must not block its deferral
            if i not in cand_set and o.type not in ("feed", "fetch"):
                consumed.update(o.input_arg_names)
        deferred = [i for i in cand
                    if not (set(block.ops[i].output_arg_names) & consumed)]
        blocked = [block.ops[i].type for i in cand if i not in set(deferred)]
        if blocked:
            raise NotImplementedError(
                f"host-side op(s) {blocked} feed device ops, so they cannot "
                f"be deferred to fetch time on this callback-less platform; "
                f"run this program on CPUPlace")
        ops = [block.ops[i] for i in deferred]
        deferred_outs = set()
        for o in ops:
            deferred_outs.update(o.output_arg_names)
        # inputs the host pass needs, by source (an input produced by an
        # EARLIER deferred op is computed host-side, not fetched)
        need = []
        for o in ops:
            for n in o.input_arg_names:
                if n not in need:
                    need.append(n)
        from_feed = [n for n in need if n in feed]
        from_dev = [n for n in need
                    if n not in feed and n not in deferred_outs
                    and block.has_var(n)]
        dev_fetch = [f for f in fetch_names if f not in deferred_outs]
        extra = [n for n in from_dev if n not in dev_fetch]
        ck = (program._uuid, program.version, tuple(deferred))
        pruned = self._host_eval_cache.get(ck)
        if pruned is None:
            pruned = program.clone()
            blk = pruned.global_block()
            keep = [o for i, o in enumerate(blk.ops) if i not in set(deferred)]
            blk.ops = keep
            self._host_eval_cache[ck] = pruned
            from ..flags import flag as _flagv

            if len(self._host_eval_cache) > _flagv("FLAGS_executor_cache_capacity"):
                self._host_eval_cache.pop(next(iter(self._host_eval_cache)))
        plan = {"ops": ops, "from_feed": from_feed, "extra": extra,
                "dev_fetch": dev_fetch, "want": list(fetch_names),
                "block": block}
        return pruned, dev_fetch + extra, plan

    @staticmethod
    def _finish_host_eval(plan, feed, fetches, scope):
        """Evaluate the deferred sink ops on CPU from fetched inputs and
        reassemble the originally-requested fetch order.  Persistable
        outputs (metric accumulators) are written back to the scope, like
        the device path's new_state write-back."""
        from .lowering import LoweringContext, lower_one

        cpu = jax.devices("cpu")[0]
        block = plan["block"]
        dev_vals = dict(zip(plan["dev_fetch"] + plan["extra"], fetches))
        ctx = LoweringContext(jax.random.PRNGKey(0), platform="cpu")
        with jax.default_device(cpu):
            env = {}
            for n in plan["from_feed"]:
                arr = np.asarray(feed[n])
                if block.has_var(n):
                    want_dt = as_np_dtype(block.var(n).dtype)
                    if want_dt is not None and arr.dtype != want_dt:
                        arr = arr.astype(want_dt)
                from ..ops.common import canon_dtype

                canon = canon_dtype(arr.dtype)
                env[n] = jnp.asarray(arr.astype(canon) if arr.dtype != canon else arr)
            for n, v in dev_vals.items():
                env[n] = jax.device_put(jnp.asarray(np.asarray(v)), cpu)
            for o in plan["ops"]:
                lower_one(ctx, o, env)
            for o in plan["ops"]:
                for n in o.output_arg_names:
                    if (n in env and block.has_var(n)
                            and getattr(block.var(n), "persistable", False)):
                        scope.set_var(n, env[n])
        return [env[n] if n in env and n not in dev_vals else dev_vals[n]
                for n in plan["want"]]

    # -- main entry ------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, np.ndarray]] = None,
        fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,  # parity arg; caching is always on
        steps: int = 1,
    ):
        """steps > 1 runs K optimizer steps in ONE device dispatch: every
        feed must carry a leading [steps] axis and fetches come back stacked
        [steps, ...].  Amortizes host/tunnel dispatch overhead the way the
        reference's dataset trainers amortize the Python boundary."""
        return self._run_impl(program, feed, fetch_list, scope, return_numpy,
                              steps, async_mode=False)

    def run_async(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, np.ndarray]] = None,
        fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
        scope: Optional[Scope] = None,
        use_program_cache: bool = True,
        steps: int = 1,
    ) -> List["FetchHandle"]:
        """`run`, minus the blocking tail: returns one `FetchHandle` per
        fetch as soon as the step is ENQUEUED on the device.  The scope is
        updated immediately with the step's (in-flight) output buffers and
        the advanced RNG key, so the next `run`/`run_async` over the same
        scope chains correctly — values, optimizer accumulators, and RNG
        advance exactly as under the synchronous path.  Device->host
        copies, deferred host-eval ops, and the FLAGS_check_nan_inf guard
        all run at handle resolution (`handle.numpy()`); an in-flight
        error therefore surfaces on resolution, not dispatch.  See
        paddle_tpu/pipeline.py:train_loop for the bounded-depth driver."""
        return self._run_impl(program, feed, fetch_list, scope, True,
                              steps, async_mode=True)

    def _run_impl(
        self,
        program: Optional[Program],
        feed: Optional[Dict[str, np.ndarray]],
        fetch_list: Optional[Sequence[Union[str, Variable]]],
        scope: Optional[Scope],
        return_numpy: bool,
        steps: int,
        async_mode: bool,
    ):
        program = program if program is not None else default_main_program()
        mesh = None
        batch_axis = "dp"
        remat = False
        local_sgd_every = 0
        grad_overlap = None
        if hasattr(program, "program") and hasattr(program, "mesh"):  # CompiledProgram
            mesh = program.mesh
            batch_axis = getattr(program, "batch_axis", "dp")
            bs = getattr(program, "build_strategy", None)
            # BuildStrategy.memory_optimize -> rematerialized backward
            # (the XLA-native descendant of the reference's
            # memory_optimize_pass: trade FLOPs for activation memory)
            remat = bool(getattr(bs, "memory_optimize", False))
            local_sgd_every = int(getattr(program, "local_sgd_every", 0) or 0)
            ov_mode = getattr(program, "grad_overlap_mode", None)
            if ov_mode:
                bucket_mb = float(getattr(program, "grad_overlap_bucket_mb", 0.0))
                grad_overlap = (ov_mode, int(bucket_mb * 1e6))
            program = program.program
        if local_sgd_every:
            if steps == 1:
                steps = local_sgd_every  # one dispatch = one LocalSGD round
            elif steps != local_sgd_every:
                raise ValueError(
                    f"with_local_sgd(sync_every={local_sgd_every}): each "
                    f"dispatch runs exactly one round; pass steps="
                    f"{local_sgd_every} (got {steps}) with feeds stacked "
                    f"[sync_every, ...]")
        scope = scope if scope is not None else global_scope()
        feed = feed or {}
        fetch_names = [f.name if isinstance(f, Variable) else str(f) for f in (fetch_list or [])]

        device = self.place.jax_device()
        block = program.global_block()

        # Convert feeds to host arrays with the declared var dtype.
        # Ragged feeds (LoDTensor / list of per-sequence arrays) expand into
        # the padded carrier + `<name>@LOD` lengths pair (paddle_tpu/lod.py).
        from ..lod import LoDTensor, lod_var_name

        expanded = {}
        for name, value in feed.items():
            declared_ragged = block.has_var(name) and block.var(name).lod_level >= 1
            is_ragged_feed = isinstance(value, LoDTensor) or (
                declared_ragged
                and isinstance(value, (list, tuple))
                and len(value) > 0
                and all(isinstance(s, np.ndarray) for s in value)
            )
            if steps > 1 and is_ragged_feed:
                raise ValueError(
                    f"steps>1 does not support ragged/LoDTensor feeds (got one for "
                    f"'{name}'): the padded expansion has no [steps] axis. Stack "
                    f"pre-padded dense arrays [steps, b, T, ...] plus the lengths "
                    f"companion instead, or run with steps=1."
                )
            if is_ragged_feed:
                lt = value if isinstance(value, LoDTensor) else LoDTensor(value)
                padded, lens = lt.padded(bucket=True)
                expanded[name] = padded
                expanded[lod_var_name(name)] = lens
            else:
                expanded[name] = value
        feed = expanded

        from ..ops.common import canon_dtype

        jfeeds = {}
        for name, value in feed.items():
            if isinstance(value, jax.Array):
                # device-resident feed: trust caller's placement (a
                # DataLoader prefetched it, or fake-data benchmarking)
                jfeeds[name] = value
                continue
            dtype = None
            if block.has_var(name):
                dtype = as_np_dtype(block.var(name).dtype)
            arr = np.asarray(value)
            if dtype is not None and arr.dtype != dtype:
                arr = arr.astype(dtype)
            # x32 canonicalization at the feed boundary (silences jax's
            # per-call int64-truncation warning)
            canon = canon_dtype(arr.dtype)
            if arr.dtype != canon:
                arr = arr.astype(canon)
            jfeeds[name] = arr

        if steps > 1:
            for name, value in jfeeds.items():
                shape = np.shape(value)
                if len(shape) == 0 or shape[0] != steps:
                    raise ValueError(
                        f"steps={steps} requires every feed to carry a leading "
                        f"[steps] axis; feed '{name}' has shape {shape}. Stack K "
                        f"batches along axis 0 (fetches come back stacked the "
                        f"same way)."
                    )

        # Fetch-time host evaluation (VERDICT r4 #5): on platforms without
        # host send/recv (the axon TPU tunnel), metric/data-transform ops
        # that are pure sinks (chunk_eval, detection_map, py_func — outputs
        # feed nothing downstream) are pruned from the device program and
        # evaluated on CPU from the fetched inputs instead of poisoning the
        # TPU program with a callback that cannot run.
        host_plan = None
        if steps == 1 and mesh is None:
            program, fetch_names, host_plan = self._split_host_eval(
                program, fetch_names, feed)

        key = scope.find_var(RNG_STATE_VAR)
        if key is None:
            seed = program.random_seed if program.random_seed is not None else 0
            key = jax.random.PRNGKey(seed)
        if mesh is None:
            key = jax.device_put(key, device)
        # (mesh path: _CompiledStep reshards the key onto the mesh itself)

        # NOTE: state shapes/dtypes are deliberately NOT in the key — the
        # inner jax.jit retraces on aval changes anyway; keying on them
        # would cost a walk over every persistable per step.
        cache_key = (
            program._uuid,
            program.version,
            tuple(sorted((n, v.shape, str(v.dtype)) for n, v in jfeeds.items())),
            tuple(fetch_names),
            scope._uuid,
            (tuple(mesh.shape.items()), batch_axis) if mesh is not None else None,
            steps,
            remat,
            local_sgd_every,
            grad_overlap,
            _lowering_flags(),
        )
        # the bookkeeping lock covers only the dict operations: a HIT (the
        # serving steady state) never waits behind a concurrent miss's
        # verify/build, which a hot reload's staged warm would otherwise
        # stretch into a traffic stall
        with self._cache_lock:
            compiled = self._cache.pop(cache_key, None)
            if compiled is not None:
                self._cache[cache_key] = compiled  # re-insert: true LRU order
                _MON.counter("executor.cache_hit").inc()
        cache_hit = compiled is not None
        if compiled is None:
            mesh_platform = (
                mesh.devices.flat[0].platform if mesh is not None else device.platform
            )
            # Static analysis ahead of lowering (FLAGS_verify_program):
            # once per compile-cache miss, so steady state pays nothing.
            # A malformed program raises a classified error naming the
            # op/var/block here instead of dying inside JAX tracing.
            from ..flags import flag as _flagv

            verify_level = _flagv("FLAGS_verify_program")
            if verify_level not in ("", "off"):
                from .analysis import check_program

                with _MON.span("analysis.verify", program=program._uuid[:8]):
                    check_program(program, level=verify_level,
                                  feed_names=list(jfeeds),
                                  fetch_names=fetch_names)
            if mesh is None:
                # Static OOM pre-check (FLAGS_resource_precheck): the
                # liveness plan predicts peak HBM for THIS (program, feed
                # shapes) pair and raises classified ResourceError naming
                # the watermark ops when it cannot fit the device — before
                # the trace/compile below allocates anything.  Mesh runs
                # skip it: per-device residency depends on sharding, which
                # the single-device plan would overstate.
                from .resource_plan import precheck_program

                with _MON.span("analysis.plan", program=program._uuid[:8]):
                    precheck_program(
                        program,
                        {n: np.shape(v) for n, v in jfeeds.items()},
                        fetch_names, steps=steps, device=device)
            with _MON.span("executor.build", program=program._uuid[:8]):
                compiled = _CompiledStep(
                    program, list(jfeeds), fetch_names, scope,
                    mesh=mesh, batch_axis=batch_axis,
                    feed_shapes={n: v.shape for n, v in jfeeds.items()},
                    n_steps=steps, remat=remat, platform=mesh_platform,
                    local_sgd=bool(local_sgd_every),
                    grad_overlap=grad_overlap,
                )
            with self._cache_lock:
                existing = self._cache.get(cache_key)
                if existing is not None:
                    # a racing thread built this signature while we did:
                    # adopt its entry so the signature keeps ONE
                    # _CompiledStep (its _build_lock then keeps XLA
                    # compiles single too); our duplicate build was cheap
                    # (no trace/compile happens until _dispatch)
                    compiled = existing
                    cache_hit = True
                    _MON.counter("executor.cache_hit").inc()
                else:
                    _MON.counter("executor.cache_miss").inc()
                    self._cache[cache_key] = compiled
                    if len(self._cache) > _flagv("FLAGS_executor_cache_capacity"):  # LRU evict
                        self._cache.pop(next(iter(self._cache)))

        if mesh is None:
            # Single-device: pin feeds and any host-resident state.
            jfeeds = {
                n: v if isinstance(v, jax.Array) else jax.device_put(jnp.asarray(v), device)
                for n, v in jfeeds.items()
            }
            for n in compiled.state_in_names:
                v = scope.find_var(n)
                if not isinstance(v, jax.Array):
                    # owned copy, NOT device_put: on CPU, device_put can
                    # alias the numpy buffer zero-copy, and rw state is
                    # DONATED — XLA reusing/freeing memory the caller
                    # (checkpoint snapshot, resilience restore) still
                    # references corrupts it in place
                    with jax.default_device(device):
                        scope.set_var(n, jnp.array(v, copy=True))
        elif compiled.multiprocess:
            # Cross-process mesh: every process contributes its LOCAL slice
            # of batch-sharded feeds (reference: per-trainer data shards in
            # NCCL2 mode); replicated feeds pass the full array everywhere.
            jfeeds = {
                n: v if isinstance(v, jax.Array)
                else jax.make_array_from_process_local_data(
                    compiled.feed_specs[n], np.asarray(v))
                for n, v in jfeeds.items()
            }
        else:
            # SPMD: shard feeds up front; jit's in_shardings places state.
            jfeeds = {
                n: v if isinstance(v, jax.Array) and v.sharding == compiled.feed_specs[n]
                else jax.device_put(v, compiled.feed_specs[n])
                for n, v in jfeeds.items()
            }

        # one tail for both modes; mon_on guards only the timing hooks, so
        # the disabled fast path stays branch-only (no blocking, no records)
        # while the monitored per-phase breakdown cannot diverge from it.
        # Monitored: execute is blocked to completion so device compute
        # isn't attributed to the fetch copy; lower/compile are timed
        # inside _dispatch when an executable is (re)built.
        mon_on = _MON.enabled
        if mon_on:
            u8 = program._uuid[:8]
            feed_bytes = int(sum(getattr(v, "nbytes", 0) for v in jfeeds.values()))
            _MON.counter("executor.feed_bytes").inc(feed_bytes)
            # dispatch-attempt census BEFORE the (possibly collective-
            # blocking) dispatch: the heartbeat's beat payload reads this,
            # and it is what makes a slow-but-alive rank's lag visible
            # while its peers sit blocked inside the collective
            _MON.counter("executor.steps_started").inc()
            ts_dispatch = time.time()
            t_run0 = time.perf_counter()
        # dispatch is watchdog-guarded: on backends whose dispatch blocks
        # (CPU/gloo cross-process collectives), a dead peer wedges the
        # enqueue itself — the guard turns that into PeerFailureError.
        # With the health layer off (every single-process run) this is a
        # direct call behind one None-check.
        fetches, new_key = _guard_blocking(
            lambda: compiled(scope, jfeeds, key), what="executor.dispatch")
        if mon_on:
            # dispatch = enqueue-only cost (what run_async pays on the
            # critical path); execute additionally blocks to completion so
            # device compute isn't attributed to the fetch copy.
            build_s = (compiled.last_lower_s + compiled.last_compile_s
                       if compiled.last_recompiled else 0.0)
            t_dispatch = time.perf_counter() - t_run0 - build_s
            _MON.observe("executor.dispatch", t_dispatch, program=u8)
        scope.set_var(RNG_STATE_VAR, new_key)
        if async_mode:
            pending = _PendingFetches(fetch_names, fetches, new_key,
                                      host_plan, feed, scope,
                                      program._uuid[:8])
            if mon_on:
                rec = {
                    "program": u8,
                    "steps": steps,
                    "async": True,
                    "cache_hit": cache_hit,
                    "recompiled": compiled.last_recompiled,
                    "cache_hits_total": _MON.counter("executor.cache_hit").value,
                    "cache_misses_total": _MON.counter("executor.cache_miss").value,
                    "recompiles_total": _MON.counter("executor.recompile").value,
                    "t_lower_s": compiled.last_lower_s if compiled.last_recompiled else 0.0,
                    "t_compile_s": compiled.last_compile_s if compiled.last_recompiled else 0.0,
                    "t_dispatch_s": t_dispatch,
                    "ts_dispatch": ts_dispatch,
                    "feed_bytes": feed_bytes,
                }
                if compiled.csig is not None:
                    rec["csig"] = compiled.csig
                _MON.record_step(rec)
            return [FetchHandle(pending, i, n)
                    for i, n in enumerate(pending.want_names)]
        if mon_on:
            _guard_blocking(lambda: jax.block_until_ready(fetches),
                            what="executor.execute")
            t_disp = time.perf_counter() - t_run0
            t_execute = t_disp - build_s
            _MON.observe("executor.execute", t_execute, program=u8)
        if host_plan is not None:
            with _MON.span("executor.host_eval"):
                fetches = self._finish_host_eval(host_plan, feed, fetches, scope)
            fetch_names = host_plan["want"]
        def _fetch_out():
            # the NaN guard's np.asarray is itself the blocking copy, so
            # it lives inside the watchdog guard with the fetch
            self._check_nan_inf(fetch_names, fetches)
            return ([np.asarray(f) for f in fetches] if return_numpy
                    else list(fetches))

        if not mon_on:
            return _guard_blocking(_fetch_out, what="executor.fetch")
        t_f0 = time.perf_counter()
        out = _guard_blocking(_fetch_out, what="executor.fetch")
        t_fetch = time.perf_counter() - t_f0
        _MON.observe("executor.fetch", t_fetch, program=u8)
        t_total = time.perf_counter() - t_run0
        _MON.observe(f"executor.run[{u8}]", t_total)
        _MON.gauge("executor.last_step_s").set(t_execute)
        rec = {
            "program": u8,
            "steps": steps,
            "cache_hit": cache_hit,
            "recompiled": compiled.last_recompiled,
            "cache_hits_total": _MON.counter("executor.cache_hit").value,
            "cache_misses_total": _MON.counter("executor.cache_miss").value,
            "recompiles_total": _MON.counter("executor.recompile").value,
            "t_lower_s": compiled.last_lower_s if compiled.last_recompiled else 0.0,
            "t_compile_s": compiled.last_compile_s if compiled.last_recompiled else 0.0,
            "t_dispatch_s": t_dispatch,
            "t_execute_s": t_execute,
            "t_fetch_s": t_fetch,
            "t_total_s": t_total,
            "ts_dispatch": ts_dispatch,
            "feed_bytes": feed_bytes,
        }
        if compiled.csig is not None:
            rec["csig"] = compiled.csig
        _MON.record_step(rec)
        return out

    @staticmethod
    def _check_nan_inf(fetch_names, fetches):
        from ..errors import NumericError
        from ..flags import flag as _flag

        if not _flag("FLAGS_check_nan_inf"):
            return
        for name, val in zip(fetch_names, fetches):
            arr = np.asarray(val)
            if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(arr).all():
                # NumericError subclasses RuntimeError, so legacy callers
                # catching the guard's historical type keep working
                raise NumericError(
                    f"FLAGS_check_nan_inf: fetch {name!r} contains "
                    f"NaN/Inf (reference CheckTensorNANOrInf)")

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """reference executor.py:892 train_from_dataset — file-list-driven
        training loop over a Dataset (paddle_tpu/dataset.py)."""
        from ..dataset import train_from_dataset as _tfd

        return _tfd(self, program if program is not None else default_main_program(),
                    dataset, scope=scope, fetch_list=fetch_list,
                    fetch_info=fetch_info, print_period=print_period)

    def infer_from_dataset(self, program=None, dataset=None, scope=None, **kw):
        return self.train_from_dataset(program, dataset, scope, **kw)
