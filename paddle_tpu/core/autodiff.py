"""append_backward / calc_gradient.

Reference: python/paddle/fluid/backward.py (append_backward:432) walks ops in
reverse emitting grad OpDescs from per-op GradOpMakers.

TPU-first redesign: there are no grad ops.  `append_backward` records ONE
`backward` op in the program naming (loss, params, grad vars); at lowering
time the executor wraps the forward segment in `jax.vjp`
(core/lowering.py:run_block_with_backward), so the gradient program is
derived by a functional transform, is always consistent with the forward
lowering, and fuses with it in XLA.  The user-visible contract is identical:
after append_backward, `<param>@GRAD` variables exist and optimizer ops can
read them.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .program import Parameter, Variable

GRAD_SUFFIX = "@GRAD"


def _grad_name(name: str) -> str:
    return name + GRAD_SUFFIX


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence] = None,
    no_grad_set: Optional[set] = None,
    callbacks=None,
) -> List[Tuple[Variable, Variable]]:
    block = loss.block
    program = block.program
    no_grad = set()
    for item in no_grad_set or ():
        no_grad.add(item.name if isinstance(item, Variable) else str(item))

    if parameter_list is not None:
        params = []
        for p in parameter_list:
            params.append(block.var(p) if isinstance(p, str) else p)
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    params = [p for p in params if p.name not in no_grad]
    if not params:
        raise ValueError("append_backward: no trainable parameters found")

    param_names = [p.name for p in params]
    grad_names = [_grad_name(n) for n in param_names]
    grads = []
    for p, gname in zip(params, grad_names):
        g = block.create_var(gname, shape=p.shape, dtype=p.dtype)
        grads.append(g)

    block.append_op(
        "backward",
        inputs={"Loss": [loss.name]},
        outputs={"Grads": grad_names},
        attrs={
            "loss_name": loss.name,
            "param_names": param_names,
            "grad_names": grad_names,
            "sparse_param_names": _find_sparse_params(block, param_names),
        },
    )
    return list(zip(params, grads))


def _find_sparse_params(block, param_names) -> List[str]:
    """Params eligible for SelectedRows gradients (reference: lookup_table
    W grads are SelectedRows when is_sparse=True, lookup_table_op.cc).  A
    param qualifies only if EVERY read of it is an is_sparse lookup_table —
    any other consumer (weight tying, dense reuse) needs the dense vjp path."""
    pset = set(param_names)
    sparse_ok: dict = {}
    program = block.program

    def scan(blk):
        for op in blk.ops:
            for slot, names in op.inputs.items():
                for n in names:
                    if n not in pset:
                        continue
                    is_sparse_lookup = (
                        op.type in ("lookup_table", "lookup_table_v2")
                        and slot == "W"
                        and bool(op.attrs.get("is_sparse", False))
                    )
                    sparse_ok[n] = sparse_ok.get(n, True) and is_sparse_lookup
            # sub-block reads count too (a tied table consumed densely inside
            # a While/cond body must stay on the dense vjp path)
            sub = op.attrs.get("sub_block")
            if sub is not None and program is not None:
                scan(program.blocks[sub])

    scan(block)
    return sorted(n for n, ok in sparse_ok.items() if ok)


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of `targets` w.r.t. arbitrary `inputs` (backward.py:672).

    Emits its own backward region; a program may hold several (e.g.
    calc_gradient + optimizer.minimize) — the lowering runs each region
    over the shared op prefix with a pinned RNG stream.
    """
    if isinstance(targets, Variable):
        targets = [targets]
    if isinstance(inputs, Variable):
        inputs = [inputs]
    if target_gradients is not None and len(target_gradients) != len(targets):
        raise ValueError("calc_gradient: target_gradients must match targets")
    if len(targets) == 1 and target_gradients is None:
        loss = targets[0]
    else:
        # multiple targets / weighted cotangents: d/dx sum_i <t_i, tg_i>
        # is exactly the requested vjp — build the combined scalar with
        # program ops so one backward region covers it
        block0 = targets[0].block
        parts = []
        for i, t in enumerate(targets):
            v = t
            tg = target_gradients[i] if target_gradients is not None else None
            if tg is not None:  # None entry = all-ones cotangent (reference)
                w = block0.create_var(shape=t.shape, dtype=t.dtype)
                block0.append_op("elementwise_mul",
                                 inputs={"X": [t.name], "Y": [tg.name]},
                                 outputs={"Out": [w.name]}, attrs={"axis": -1})
                v = w
            r = block0.create_var(shape=(1,), dtype=t.dtype)
            block0.append_op("reduce_sum", inputs={"X": [v.name]},
                             outputs={"Out": [r.name]}, attrs={"reduce_all": True})
            parts.append(r)
        if len(parts) == 1:
            loss = parts[0]
        else:
            loss = block0.create_var(shape=(1,), dtype=targets[0].dtype)
            block0.append_op("sum", inputs={"X": [p.name for p in parts]},
                             outputs={"Out": [loss.name]})
    block = loss.block
    param_names = [v.name for v in inputs]
    grad_names = [_grad_name(n) for n in param_names]
    grads = []
    for v, gname in zip(inputs, grad_names):
        grads.append(block.create_var(gname, shape=v.shape, dtype=v.dtype))
    block.append_op(
        "backward",
        inputs={"Loss": [loss.name]},
        outputs={"Grads": grad_names},
        attrs={
            "loss_name": loss.name,
            "param_names": param_names,
            "grad_names": grad_names,
        },
    )
    return grads
