"""Static resource planner: liveness-based peak-HBM + per-op cost model.

PR 6 gave every program build-time shapes and dtypes (core/analysis.py);
this module is the first QUANTITATIVE consumer.  The reference stack runs
exactly this analysis at build time — Fluid's `memory_optimize` / inplace
passes compute def/last-use liveness over the op graph to reuse buffers —
and XLA does it again internally as ahead-of-time buffer assignment.  The
TPU rebuild needs the numbers OUTSIDE the compiler, before it runs:

  * **Liveness / peak HBM** (`plan_program`): every non-persistable value
    gets a def/last-use interval over its block; persistables (params,
    optimizer state, BN stats) are resident for the whole program;
    donated in-place updates (an op writing the same persistable it
    reads — the executor's `rw_names` donation set, the classes
    `tools/donation_audit.py` audits) are counted ONCE, while a written-
    but-never-read persistable costs a transient double buffer at its
    writer exactly as XLA cannot alias it.  Sub-block (while /
    conditional_block / dynamic_rnn) temps peak inside the owning op and
    die at loop exit; loop-carried and escaping names follow the same
    seeding rules as the verifier.  A `backward` op extends every earlier
    temp's range to itself (activations saved for the VJP) and defines
    the gradient buffers its attrs name.  The result is a `ResourcePlan`
    with a peak-HBM estimate and per-op live-set watermarks naming the
    ops and buffers AT the peak.

  * **Op cost model**: per-op FLOPs and HBM traffic from cost rules
    registered beside the `infer=` rules in ops/* (`registry.set_cost`,
    `register_cost` + factories below; `DEFAULT_COST` covers unregistered
    elementwise-ish ops and is tracked by `cost_coverage`).  Rolled up to
    an analytic roofline step time — per op, time = max(flops/peak_flops,
    bytes/hbm_bandwidth); ops ahead of a `backward` count 3x (fwd + 2x
    bwd) — and a `predicted_mfu`: the MFU this program could reach at
    roofline, the yardstick `perf_report --check-bench` holds measured
    MFU against.

Consumers: the executor pre-checks every compile-cache miss and raises
classified `errors.ResourceError` (phase=build) naming the watermark ops
when the plan exceeds device HBM — before XLA compiles or allocates
anything (`precheck_program`, FLAGS_resource_precheck /
FLAGS_resource_hbm_limit_mb); `serving/registry.py` budgets model loads
on plan bytes for the bucket shapes it will warm (weights + activations,
not manifest weight bytes alone); `tools/resource_plan.py` renders /
CI-gates plans over the model zoo and calibrates them against measured
truth (XLA `memory_analysis` buffer assignment on CPU, memstats
`device_bytes_in_use` high-water on device) — the tolerance band there
is the ratchet.

Estimates are deliberately CONSERVATIVE upper bounds: XLA fusion
materializes fewer intermediates than the op graph names.  The
calibration gate states how conservative (see tools/resource_plan.py
CALIBRATION_RATIO_LO/HI).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..errors import ResourceError
from ..monitor import MONITOR as _MON
from . import registry
from .analysis import STRUCTURAL_OPS
from .dtypes import as_np_dtype
from .program import Block, Parameter, Program

__all__ = [
    # chip model
    "CHIP_PEAK_FLOPS", "CHIP_HBM_BANDWIDTH", "CHIP_HBM_BYTES",
    # cost rules
    "CostContext", "as_cost", "register_cost", "register_elementwise_cost",
    "register_bytes_cost", "register_state_update_cost", "cost_coverage",
    "op_cost",
    # planner
    "ShapeEnv", "PlanRow", "ResourcePlan", "plan_program",
    # consumers
    "device_hbm_limit", "precheck_program",
]

# Chip model (v5e-class single chip; bench.py's V5E_BF16_PEAK is the same
# peak).  The roofline is a yardstick, not a simulator: one dense-unit
# peak, one HBM stream.
CHIP_PEAK_FLOPS = 197e12     # bf16 dense peak, FLOP/s
CHIP_HBM_BANDWIDTH = 819e9   # bytes/s
CHIP_HBM_BYTES = 16e9        # HBM capacity

DYN = -1

# Sub-block-owning op types whose body executes under the op (the same
# vocabulary the verifier walks).
_SUB_BLOCK_OPS = ("while", "conditional_block", "dynamic_rnn", "pipeline")


def _itemsize(dtype_name: Optional[str]) -> int:
    if not dtype_name:
        return 4
    if "float16" in dtype_name or dtype_name == "bfloat16":
        return 2
    try:
        return np.dtype(as_np_dtype(dtype_name)).itemsize
    except TypeError:
        return 2  # bfloat16-class dtypes numpy can't name


def _elems(shape: Optional[Sequence[int]]) -> int:
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= max(int(d), 1)
    return n


class ShapeEnv:
    """Concrete per-var byte sizes: declared shapes with dynamic (-1) dims
    bound from the feed shapes (the batch, plus the bucketed time dim the
    LoD carrier pads).  Feeds take their ACTUAL shapes; everything else
    takes its declared shape with each -1 replaced by the batch size."""

    def __init__(self, program: Program, feed_shapes: Optional[Dict[str, tuple]] = None,
                 steps: int = 1):
        self.program = program
        self.steps = max(int(steps), 1)
        raw = {n: tuple(int(d) for d in s)
               for n, s in (feed_shapes or {}).items()}
        self.feed_bytes_shapes = dict(raw)  # with any leading [steps] axis
        if self.steps > 1:  # per-step shapes bind the batch dim
            raw = {n: s[1:] if len(s) > 0 else s for n, s in raw.items()}
        self.feed_shapes = raw
        self._vars: Dict[str, Any] = {}
        for blk in program.blocks:
            for n, v in blk.vars.items():
                self._vars.setdefault(n, v)
        batch = None
        for n, s in raw.items():
            v = self._vars.get(n)
            if (v is not None and v.shape and len(v.shape) > 0
                    and v.shape[0] == DYN and s):
                batch = int(s[0])
                break
        if batch is None:
            for s in raw.values():
                if s:
                    batch = int(s[0])
                    break
        self.batch = batch or 1

    def var(self, name: str):
        return self._vars.get(name)

    def shape(self, name: str) -> Optional[Tuple[int, ...]]:
        if name in self.feed_shapes:
            return self.feed_shapes[name]
        v = self._vars.get(name)
        if v is None or v.shape is None:
            return None
        return tuple(self.batch if int(d) == DYN else int(d) for d in v.shape)

    def dtype(self, name: str) -> Optional[str]:
        v = self._vars.get(name)
        return None if v is None else v.dtype

    def nbytes(self, name: str) -> int:
        s = self.shape(name)
        if s is None:
            return 0
        return _elems(s) * _itemsize(self.dtype(name))

    def feed_resident_bytes(self) -> int:
        """Bytes the staged feeds pin (with any [steps] stacking)."""
        total = 0
        for n, s in self.feed_bytes_shapes.items():
            total += _elems(s) * _itemsize(self.dtype(n))
        return total


# --------------------------------------------------------------------------
# per-op cost rules
# --------------------------------------------------------------------------

class CostContext:
    """Handed to cost rules: slot-level access to CONCRETE shapes (dynamic
    dims bound via ShapeEnv) plus byte-traffic helpers."""

    def __init__(self, op, block: Block, env: ShapeEnv):
        self.op = op
        self.block = block
        self.env = env

    def attr(self, name, default=None):
        return self.op.attr(name, default)

    def in_name(self, slot: str, i: int = 0) -> Optional[str]:
        names = self.op.input(slot)
        return names[i] if i < len(names) else None

    def out_name(self, slot: str, i: int = 0) -> Optional[str]:
        names = self.op.output(slot)
        return names[i] if i < len(names) else None

    def in_shape(self, slot: str, i: int = 0) -> Optional[Tuple[int, ...]]:
        n = self.in_name(slot, i)
        return None if n is None else self.env.shape(n)

    def out_shape(self, slot: str, i: int = 0) -> Optional[Tuple[int, ...]]:
        n = self.out_name(slot, i)
        return None if n is None else self.env.shape(n)

    def in_elems(self, slot: str, i: int = 0) -> int:
        return _elems(self.in_shape(slot, i))

    def out_elems(self, slot: str, i: int = 0) -> int:
        return _elems(self.out_shape(slot, i))

    def out_elems_total(self) -> int:
        return sum(_elems(self.env.shape(n))
                   for n in self.op.output_arg_names)

    def io_bytes(self) -> int:
        """Default HBM traffic: every distinct input read once + every
        distinct output written once."""
        total = 0
        for n in dict.fromkeys(self.op.input_arg_names):
            total += self.env.nbytes(n)
        for n in dict.fromkeys(self.op.output_arg_names):
            total += self.env.nbytes(n)
        return total


def as_cost(rule):
    """Adapt rule(ctx) -> (flops, bytes) to the registry's CostFn."""

    def cost(op, block, env):
        return rule(CostContext(op, block, env))

    cost._cost_rule = rule
    return cost


def register_cost(types: Sequence[str], rule):
    """Attach one cost rule to several registered op types."""
    fn = as_cost(rule)
    for t in types:
        registry.set_cost(t, fn)
    return fn


def register_elementwise_cost(*types, flops_per_elem: float = 1.0):
    """flops_per_elem per OUTPUT element; traffic = inputs + outputs once.
    Right for the unary/binary/compare/activation families (and the
    transcendental ones with a higher flops_per_elem)."""

    def rule(ctx: CostContext):
        return flops_per_elem * ctx.out_elems_total(), ctx.io_bytes()

    return register_cost(types, rule)


def register_bytes_cost(*types):
    """Pure data movement (reshape/cast/concat/transpose/gather...):
    zero FLOPs, traffic = inputs + outputs."""

    def rule(ctx: CostContext):
        return 0.0, ctx.io_bytes()

    return register_cost(types, rule)


def register_state_update_cost(*types, flops_per_elem: float = 4.0):
    """Optimizer-style updates: a few FLOPs per parameter element; traffic
    = every state slot read + its `<Slot>Out` written (which io_bytes
    already counts, donated or not — in-place aliasing saves RESIDENCY,
    not traffic)."""

    def rule(ctx: CostContext):
        return flops_per_elem * ctx.in_elems("Param"), ctx.io_bytes()

    return register_cost(types, rule)


# Unregistered op types fall back to 1 FLOP per output element + io
# traffic — right for elementwise-ish stragglers, and tracked by
# `cost_coverage` so the CLI gate names what is uncovered.
def _default_cost(op, block, env):
    ctx = CostContext(op, block, env)
    return float(ctx.out_elems_total()), float(ctx.io_bytes())


def op_cost(op, block: Block, env: ShapeEnv) -> Tuple[float, float, bool]:
    """(flops, traffic_bytes, covered) for one op."""
    d = registry.get_op_def_or_none(op.type)
    if d is None or d.cost is None:
        f, b = _default_cost(op, block, env)
        return f, b, False
    f, b = d.cost(op, block, env)
    return float(f), float(b), True


def cost_coverage(programs: Sequence[Program]) -> Dict[str, Any]:
    """Fraction of op TYPES appearing in `programs` that have a registered
    cost rule (same shape as analysis.infer_coverage; feed/fetch/backward
    are structural and exempt — backward's cost is the 3x grad factor)."""
    types = set()
    for p in programs:
        for blk in p.blocks:
            for op in blk.ops:
                if op.type not in STRUCTURAL_OPS:
                    types.add(op.type)
    covered = sorted(
        t for t in types
        if (registry.get_op_def_or_none(t) is not None
            and registry.get_op_def_or_none(t).cost is not None))
    missing = sorted(types - set(covered))
    return {"covered_types": covered, "missing_types": missing,
            "frac": (len(covered) / len(types)) if types else 1.0}


# --------------------------------------------------------------------------
# the plan
# --------------------------------------------------------------------------

@dataclass
class PlanRow:
    """One op's contribution: cost + the live set AT this op."""

    op_idx: int
    op_type: str
    flops: float            # forward FLOPs (before the grad factor)
    traffic_bytes: float    # forward HBM traffic
    grad_factor: int        # 3 when a later `backward` differentiates this op
    live_bytes: int         # temps live at this op (+ sub-block peak here)
    cost_covered: bool


@dataclass
class ResourcePlan:
    """Static resource estimate for one (program, feed shapes) pair."""

    batch: int
    steps: int
    persistable_bytes: int
    feed_bytes: int
    peak_bytes: int              # persistable + feeds + peak live temps
    peak_temp_bytes: int
    peak_op_idx: Optional[int]
    peak_op_type: Optional[str]
    # the buffers live at the peak, largest first:
    # {var, bytes, def_op_idx, def_op_type}
    watermark: List[dict] = field(default_factory=list)
    rows: List[PlanRow] = field(default_factory=list)
    flops_total: float = 0.0           # grad-factored
    traffic_bytes_total: float = 0.0   # grad-factored
    roofline_step_s: float = 0.0
    predicted_mfu: float = 0.0
    cost_coverage_frac: float = 1.0
    cost_missing_types: List[str] = field(default_factory=list)

    def watermark_ops(self) -> List[str]:
        """Human-readable attribution of the predicted peak: the op at the
        peak plus the def sites of the largest live buffers."""
        out = []
        if self.peak_op_idx is not None:
            out.append(f"op #{self.peak_op_idx} ({self.peak_op_type})")
        for w in self.watermark:
            if w.get("def_op_idx") is not None:
                tag = f"op #{w['def_op_idx']} ({w['def_op_type']})"
                ent = f"{w['var']} ({w['bytes'] / 1e6:.1f} MB, def {tag})"
            else:
                ent = f"{w['var']} ({w['bytes'] / 1e6:.1f} MB)"
            out.append(ent)
        return out

    def to_dict(self) -> dict:
        return {
            "batch": self.batch, "steps": self.steps,
            "persistable_bytes": self.persistable_bytes,
            "feed_bytes": self.feed_bytes,
            "peak_bytes": self.peak_bytes,
            "peak_temp_bytes": self.peak_temp_bytes,
            "peak_op_idx": self.peak_op_idx,
            "peak_op_type": self.peak_op_type,
            "watermark": list(self.watermark),
            "flops_total": self.flops_total,
            "traffic_bytes_total": self.traffic_bytes_total,
            "roofline_step_s": self.roofline_step_s,
            "predicted_mfu": self.predicted_mfu,
            "cost_coverage_frac": self.cost_coverage_frac,
            "cost_missing_types": list(self.cost_missing_types),
        }


def _plan_block(program: Program, block: Block, env: ShapeEnv,
                persistable: set, feeds: set, fetch_names: set,
                rows: Optional[List[PlanRow]] = None):
    """Liveness + cost sweep over one block.

    Returns (peak_temp_bytes, peak_op_idx, live_at_peak: {name: bytes},
    flops_rows, traffic_rows) where peak_temp_bytes covers this block's
    temps only — persistables and feeds are the caller's resident base.
    Sub-blocks contribute their own peak at the owning op and their temps
    DIE at the owning op's end (loop-carried names live in the loop's
    carry buffers, which the sub-block's own liveness covers)."""
    ops = [op for op in block.ops if op.type not in ("feed", "fetch")]
    n = len(ops)
    resident = persistable | feeds

    # pass 1: def / last-use intervals (+ grad defs, + backward extension)
    def_at: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    backward_idxs: List[int] = []
    sub_at: Dict[int, Block] = {}
    double_buffer: Dict[int, int] = {}
    for i, op in enumerate(ops):
        reads = list(op.input_arg_names)
        writes = list(op.output_arg_names)
        if op.type == "backward":
            backward_idxs.append(i)
            reads.append(op.attrs.get("loss_name"))
        for m in reads:
            if m is None or m in resident:
                continue
            last_use[m] = i
            def_at.setdefault(m, i)  # read-before-def (loop carry): resident-at-0
        ins = set(op.input_arg_names)
        for m in writes:
            if m in persistable:
                # donated in-place update (read+written) counts once in the
                # resident base; a written-but-NEVER-read persistable is the
                # donation audit's `copied_not_read` class — XLA cannot
                # alias it, so its writer pays a transient double buffer
                if m not in ins:
                    double_buffer[i] = double_buffer.get(i, 0) + env.nbytes(m)
                continue
            if m in feeds:
                continue
            def_at.setdefault(m, i)
            last_use[m] = max(last_use.get(m, i), i)
        sub_idx = op.attrs.get("sub_block")
        if (op.type in _SUB_BLOCK_OPS and isinstance(sub_idx, int)
                and 0 <= sub_idx < len(program.blocks)
                and sub_idx != block.idx):
            sub_at[i] = program.blocks[sub_idx]

    # fetched values stay live to the end of the block (copied out)
    for m in fetch_names:
        if m in def_at:
            last_use[m] = n - 1
    # activations: every temp defined before a backward op is (potentially)
    # saved for the VJP, so it stays live until the backward runs
    for bi in backward_idxs:
        for m, d in def_at.items():
            if d < bi:
                last_use[m] = max(last_use.get(m, d), bi)

    # pass 2: the sweep
    start_events: Dict[int, List[str]] = {}
    end_events: Dict[int, List[str]] = {}
    for m, d in def_at.items():
        start_events.setdefault(d, []).append(m)
        end_events.setdefault(max(last_use.get(m, d), d), []).append(m)
    live: Dict[str, int] = {}
    peak = 0
    peak_idx: Optional[int] = None
    peak_live: Dict[str, int] = {}
    live_total = 0
    has_backward = bool(backward_idxs)
    last_bwd = backward_idxs[-1] if has_backward else -1
    flops_sum = 0.0
    traffic_sum = 0.0
    for i, op in enumerate(ops):
        gf = 3 if (has_backward and i < last_bwd
                   and op.type not in STRUCTURAL_OPS) else 1
        sub = None
        if i in sub_at:
            # recurse HERE, where the owner's grad factor is known: body
            # ops ahead of a parent-block `backward` are differentiated
            # too, so their rows inherit the owner's factor.  One body
            # execution (trip counts are not static).  Loop-carried names
            # need no special seeding: a body read of a not-yet-defined
            # temp starts its interval at the read, which covers the
            # whole body — the carry buffer is live across iterations
            # either way.
            n_rows_before = len(rows) if rows is not None else 0
            sub_peak, _sp_op, sub_live, _sc = _plan_block(
                program, sub_at[i], env, persistable, feeds, fetch_names,
                rows=rows)
            if rows is not None and gf != 1:
                for r in rows[n_rows_before:]:
                    r.grad_factor *= gf
            sub = (sub_peak, sub_live)
        for m in start_events.get(i, ()):
            b = env.nbytes(m)
            if b and m not in live:
                live[m] = b
                live_total += b
        here = live_total + double_buffer.get(i, 0)
        if sub is not None:
            here += sub[0]
        if here > peak:
            peak, peak_idx = here, i
            peak_live = dict(live)
            if sub is not None:
                peak_live.update(sub[1])
            if double_buffer.get(i):
                for m in op.output_arg_names:
                    if m in persistable and m not in set(op.input_arg_names):
                        peak_live[m] = env.nbytes(m)
        if op.type == "backward":
            flops, traffic, covered = 0.0, 0.0, True
        else:
            flops, traffic, covered = op_cost(op, block, env)
        if rows is not None:
            rows.append(PlanRow(op_idx=i, op_type=op.type, flops=flops,
                                traffic_bytes=traffic, grad_factor=gf,
                                live_bytes=here, cost_covered=covered))
        flops_sum += flops * gf
        traffic_sum += traffic * gf
        for m in end_events.get(i, ()):
            b = live.pop(m, 0)
            live_total -= b
    return peak, peak_idx, peak_live, (flops_sum, traffic_sum)


def plan_program(program: Program, feed_shapes: Optional[Dict[str, tuple]] = None,
                 fetch_names: Optional[Sequence[str]] = None,
                 steps: int = 1, top_k: int = 6) -> ResourcePlan:
    """Build the ResourcePlan for one program at concrete feed shapes.

    `feed_shapes` may carry a leading [steps] axis when `steps > 1` (the
    executor's stacked multi-step dispatch); the liveness model is
    per-step (lax.scan reuses buffers) while the staged feeds count at
    their full stacked size."""
    env = ShapeEnv(program, feed_shapes, steps=steps)
    block = program.global_block()
    persistable = {v.name for v in program.list_vars() if v.persistable}
    feeds = set(env.feed_shapes)

    persistable_bytes = sum(env.nbytes(nm) for nm in sorted(persistable))
    feed_bytes = env.feed_resident_bytes()

    rows: List[PlanRow] = []
    peak_temp, peak_idx, peak_live, _costs = _plan_block(
        program, block, env, persistable, feeds,
        set(fetch_names or ()), rows=rows)

    # per-op roofline: each op bound by compute OR bandwidth, summed
    roofline = 0.0
    flops_sum = 0.0
    traffic_sum = 0.0
    for r in rows:
        flops_sum += r.flops * r.grad_factor
        traffic_sum += r.traffic_bytes * r.grad_factor
        roofline += max(r.flops * r.grad_factor / CHIP_PEAK_FLOPS,
                        r.traffic_bytes * r.grad_factor / CHIP_HBM_BANDWIDTH)
    mfu = (flops_sum / (roofline * CHIP_PEAK_FLOPS)) if roofline > 0 else 0.0

    # coverage from the sweep's own rows (every reachable op already
    # carries cost_covered — no second registry walk)
    types_seen: Dict[str, bool] = {}
    for r in rows:
        if r.op_type not in STRUCTURAL_OPS:
            types_seen[r.op_type] = types_seen.get(r.op_type, True) and r.cost_covered
    cov_missing = sorted(t for t, c in types_seen.items() if not c)
    cov_frac = ((len(types_seen) - len(cov_missing)) / len(types_seen)
                if types_seen else 1.0)
    watermark = [
        {"var": nm, "bytes": b,
         "def_op_idx": _def_idx_of(block, nm),
         "def_op_type": _def_type_of(block, nm)}
        for nm, b in sorted(peak_live.items(), key=lambda kv: -kv[1])[:top_k]
    ]
    peak_op_type = None
    if peak_idx is not None:
        runnable = [op for op in block.ops if op.type not in ("feed", "fetch")]
        if peak_idx < len(runnable):
            peak_op_type = runnable[peak_idx].type
    return ResourcePlan(
        batch=env.batch, steps=env.steps,
        persistable_bytes=int(persistable_bytes),
        feed_bytes=int(feed_bytes),
        peak_bytes=int(persistable_bytes + feed_bytes + peak_temp),
        peak_temp_bytes=int(peak_temp),
        peak_op_idx=peak_idx, peak_op_type=peak_op_type,
        watermark=watermark, rows=rows,
        flops_total=flops_sum, traffic_bytes_total=traffic_sum,
        roofline_step_s=roofline, predicted_mfu=mfu,
        cost_coverage_frac=cov_frac,
        cost_missing_types=cov_missing,
    )


def _def_idx_of(block: Block, name: str) -> Optional[int]:
    idx = 0
    for op in block.ops:
        if op.type in ("feed", "fetch"):
            continue
        if name in op.output_arg_names:
            return idx
        idx += 1
    return None


def _def_type_of(block: Block, name: str) -> Optional[str]:
    for op in block.ops:
        if op.type in ("feed", "fetch"):
            continue
        if name in op.output_arg_names:
            return op.type
    return None


# --------------------------------------------------------------------------
# the executor's OOM pre-check
# --------------------------------------------------------------------------

def device_hbm_limit(device=None) -> Optional[int]:
    """The device allocator's bytes_limit, or the FLAGS override; None when
    neither is known (XLA:CPU exposes no memory_stats)."""
    from ..flags import flag as _flag

    mb = float(_flag("FLAGS_resource_hbm_limit_mb") or 0)
    if mb > 0:
        return int(mb * 1e6)
    if device is None:
        return None
    try:
        stats = device.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return None


def precheck_program(program: Program, feed_shapes, fetch_names,
                     steps: int = 1, device=None,
                     limit_bytes: Optional[int] = None) -> Optional[ResourcePlan]:
    """The executor's compile-cache-miss OOM pre-check: plan the program
    and raise classified `ResourceError` naming the watermark ops when the
    plan cannot fit — BEFORE XLA compiles or allocates anything.  Returns
    the plan (or None when the check is off / no limit is known)."""
    from ..flags import flag as _flag

    if _flag("FLAGS_resource_precheck") in ("", "off"):
        return None
    limit = limit_bytes if limit_bytes is not None else device_hbm_limit(device)
    if not limit:
        return None
    plan = plan_program(program, feed_shapes, fetch_names, steps=steps)
    _MON.counter("analysis.resource_prechecks").inc()
    if plan.peak_bytes > limit:
        _MON.counter("analysis.resource_blocked").inc()
        marks = plan.watermark_ops()
        raise ResourceError(
            f"static resource plan predicts peak HBM "
            f"{plan.peak_bytes / 1e6:.1f} MB > limit {limit / 1e6:.1f} MB "
            f"(persistables {plan.persistable_bytes / 1e6:.1f} MB, feeds "
            f"{plan.feed_bytes / 1e6:.1f} MB, live temps "
            f"{plan.peak_temp_bytes / 1e6:.1f} MB at {marks[0] if marks else '?'}); "
            f"watermark: {'; '.join(marks)} — shrink the batch, enable "
            f"BuildStrategy.memory_optimize (remat), or shard "
            f"(raised BEFORE any XLA compile/allocate; "
            f"FLAGS_resource_precheck=off skips this check)",
            needed_bytes=plan.peak_bytes, limit_bytes=int(limit),
            watermark_ops=marks)
    return plan
