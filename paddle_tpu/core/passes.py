"""Program-rewrite pass infrastructure.

Reference: framework/ir/ — `ir::Graph` + `Pass` registry + ~60 passes
(fusions, memory opt, multi-device lowering) applied by BuildStrategy.

TPU-first: XLA owns fusion/layout/scheduling, so the reference's kernel-
fusion passes have no residue to produce — the passes that REMAIN useful
are program-level rewrites ahead of lowering: dead-op pruning, identity
elimination, algebraic folds, and structural rewrites (PipelineOptimizer's
stage cut is morally one of these).  The IR the passes walk is the Program
itself (op/var lists) — the redesign collapsed the separate ir::Graph; a
pass is any callable Program -> None mutating in place.
"""
from __future__ import annotations

import bisect
from typing import Callable, Dict, List, Optional, Sequence

_PASS_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    def deco(fn):
        _PASS_REGISTRY[name] = fn
        return fn

    return deco


def registered_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


def _verify_after(program, pass_name: str):
    """Pass-safety harness: under FLAGS_verify_program, re-verify the
    program after a rewrite so a pass bug surfaces as an immediate
    diagnostic naming the offending op/var instead of wrong numerics (or
    an opaque trace error) at lowering time."""
    from ..flags import flag

    level = flag("FLAGS_verify_program")
    if level in ("", "off"):
        return
    from .analysis import SEV_ERROR, PassVerificationError, verify_program

    diags = verify_program(program, level=level)
    errors = [d for d in diags if d.severity == SEV_ERROR]
    if errors:
        raise PassVerificationError(pass_name, errors)


def apply_pass(program, name: str, **kw):
    if name not in _PASS_REGISTRY:
        raise KeyError(f"unknown pass {name!r}; known: {registered_passes()}")
    _PASS_REGISTRY[name](program, **kw)
    _verify_after(program, name)
    return program


class PassBuilder:
    """reference core.PassBuilder (build_strategy._finalize surface): an
    ordered pass pipeline."""

    def __init__(self, passes: Optional[Sequence[str]] = None):
        self._passes: List[str] = list(passes or [])

    def append_pass(self, name: str) -> "PassBuilder":
        if name not in _PASS_REGISTRY:
            raise KeyError(f"unknown pass {name!r}")
        self._passes.append(name)
        return self

    def remove_pass(self, name: str) -> "PassBuilder":
        self._passes.remove(name)
        return self

    def all_passes(self) -> List[str]:
        return list(self._passes)

    def apply(self, program):
        """Apply the pipeline; under FLAGS_verify_program each pass is
        followed by a program verification (see `_verify_after`)."""
        for p in self._passes:
            apply_pass(program, p)
        return program


def _rewire(block, old: str, new: str, start: int):
    """Replace reads of `old` with `new` in ops from index `start` on."""
    for op in block.ops[start:]:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [new if n == old else n for n in names]


@register_pass("remove_identity_ops")
def remove_identity_ops(program, keep=()):
    """Drop `assign` and no-op `scale` (scale=1, bias=0) ops, rewiring
    same-block consumers to the producer (reference: identity-elimination
    portion of the inplace/memory passes).

    `keep`: names that must stay written (fetch targets).  Identities whose
    output is kept, persistable, or read from another block (control-flow
    sub-blocks) are conservatively left in place."""
    keep = set(keep)
    for block, outside in zip(program.blocks, _outside_reads(program)):
        # var -> index of its LAST write (one pass; keeps the hazard check
        # below O(1) per candidate instead of a tail rescan)
        last_write: Dict[str, int] = {}
        for i, op in enumerate(block.ops):
            for out_name in op.output_arg_names:
                last_write[out_name] = i
        kept = []
        for i, op in enumerate(block.ops):
            is_identity = op.type == "assign" or (
                op.type == "scale"
                and op.attrs.get("scale", 1.0) == 1.0
                and op.attrs.get("bias", 0.0) == 0.0
            )
            if not is_identity:
                kept.append(op)
                continue
            src = op.input_arg_names[0]
            dst = op.output_arg_names[0]
            dst_var = block._find_var_recursive(dst)
            if (dst in keep or dst in outside
                    or (dst_var is not None and dst_var.persistable)):
                kept.append(op)  # fetched / captured / state: not removable
                continue
            # snapshot semantics: if any later op WRITES src or dst, the
            # assign is a real copy (t = x; x += 1; use t) — rewiring reads
            # of dst to src would observe the mutation.  Keep it.
            if last_write.get(src, -1) > i or last_write.get(dst, -1) > i:
                kept.append(op)
                continue
            _rewire(block, dst, src, i + 1)
        block.ops = kept
    program._bump()


@register_pass("fold_scale_chains")
def fold_scale_chains(program):
    """Fold consecutive scale ops (y = a2*(a1*x + b1) + b2) into one
    (reference: the algebraic-simplification family of ir passes).  The
    bypassed intermediate op stays in the program (it may feed other
    consumers or fetches); the executor's compile-time prune drops it when
    genuinely dead."""
    for block in program.blocks:
        by_output = {}
        for op in block.ops:
            if op.type == "scale" and op.attrs.get("bias_after_scale", True):
                src = op.input_arg_names[0]
                prev = by_output.get(src)
                if prev is not None and prev.attrs.get("bias_after_scale", True):
                    a1 = prev.attrs.get("scale", 1.0)
                    b1 = prev.attrs.get("bias", 0.0)
                    a2 = op.attrs.get("scale", 1.0)
                    b2 = op.attrs.get("bias", 0.0)
                    op.inputs["X"] = [prev.input_arg_names[0]]
                    op.attrs["scale"] = a1 * a2
                    op.attrs["bias"] = a2 * b1 + b2
                by_output[op.output_arg_names[0]] = op
            # ANY write invalidates cached chains that read or wrote the
            # same name (in-place ops like increment would otherwise be
            # folded across — wrong numerics)
            for out in op.output_arg_names:
                if op.type != "scale" or out != op.output_arg_names[0]:
                    by_output.pop(out, None)
                stale = [k for k, v in by_output.items()
                         if v.input_arg_names[0] == out and v is not op]
                for k in stale:
                    by_output.pop(k)
    program._bump()


def _reader_counts(block):
    """name -> number of ops in `block` reading it."""
    counts: Dict[str, int] = {}
    for op in block.ops:
        for n in op.input_arg_names:
            counts[n] = counts.get(n, 0) + 1
    return counts


def _rw_positions(block):
    """(writes, reads): name -> ascending list of op indices writing/reading
    it — fuels the O(log n) intervening-access hazard checks below."""
    writes: Dict[str, list] = {}
    reads: Dict[str, list] = {}
    for i, op in enumerate(block.ops):
        for n in op.input_arg_names:
            reads.setdefault(n, []).append(i)
        for n in op.output_arg_names:
            writes.setdefault(n, []).append(i)
    return writes, reads


def _accessed_between(positions, name, lo, hi):
    """True if `name` appears in `positions` at an op index strictly between
    lo and hi (exclusive both ends)."""
    idxs = positions.get(name)
    if not idxs:
        return False
    j = bisect.bisect_right(idxs, lo)
    return j < len(idxs) and idxs[j] < hi


def _outside_reads(program):
    """Per-block sets of names read by any op OUTSIDE that block (sub-block
    capture), aligned with program.blocks: one pass over the program instead
    of an O(blocks^2) rescan of every other block's op list per block.
    Shared by remove_identity_ops and the fusion passes."""
    block_reads = []
    n_blocks_reading: Dict[str, int] = {}
    for b in program.blocks:
        reads = set()
        for op in b.ops:
            reads.update(op.input_arg_names)
        block_reads.append(reads)
        for n in reads:
            n_blocks_reading[n] = n_blocks_reading.get(n, 0) + 1
    return [{n for n, c in n_blocks_reading.items()
             if c > (1 if n in reads else 0)}
            for reads in block_reads]


@register_pass("fuse_bn_relu")
def fuse_bn_relu(program, keep=()):
    """Merge `batch_norm` -> `relu` pairs into batch_norm(fuse_relu=True)
    (reference: conv_bn_fuse / fuse_relu_depthwise_conv ir passes; here the
    relu folds into the BN epilogue so the Pallas scale/shift/relu kernel —
    or the XLA composite's fused maximum — applies it in the same pass over
    the activation).

    Safe only when the BN's Y is read by exactly that relu and nowhere else
    (any other reader still needs the pre-relu value); `keep` names fetch
    targets that must stay written."""
    keep = set(keep)
    for block, outside in zip(program.blocks, _outside_reads(program)):
        readers = _reader_counts(block)
        writes, reads = _rw_positions(block)
        by_out = {}
        for i, op in enumerate(block.ops):
            if op.type == "batch_norm" and not op.attrs.get("fuse_relu"):
                by_out[op.output("Y")[0]] = (op, i)
        kept = []
        for i, op in enumerate(block.ops):
            if op.type == "relu":
                src = op.input_arg_names[0]
                bn, bn_i = by_out.get(src, (None, -1))
                # by_out keeps the LAST batch_norm writing each Y name — it
                # must also PRECEDE this relu (a later writer is a different
                # def; pairing across it would miscompile)
                if bn is not None and bn_i >= i:
                    bn = None
                out_name = op.output("Out")[0] if bn is not None else None
                # snapshot semantics: fusing moves the write of Out from the
                # relu's position up to the BN's — any op between that reads
                # Out (old value) or writes Out, or that writes Y (so the
                # relu never saw the BN's value), makes the move observable
                hazard = bn is not None and (
                    _accessed_between(writes, src, bn_i, i)
                    or _accessed_between(writes, out_name, bn_i, i)
                    or _accessed_between(reads, out_name, bn_i, i))
                if (bn is not None and not hazard
                        and readers.get(src, 0) == 1
                        and src not in keep and src not in outside):
                    v = block._find_var_recursive(src)
                    if v is None or not v.persistable:
                        # BN now writes the relu's output var directly
                        bn.outputs["Y"] = [op.output("Out")[0]]
                        bn.attrs["fuse_relu"] = True
                        continue
            kept.append(op)
        block.ops = kept
    program._bump()


@register_pass("fuse_ln_residual")
def fuse_ln_residual(program, keep=()):
    """Fold `elementwise_add(X, Y)` -> `layer_norm` chains into
    layer_norm(X, Residual=Y) (reference: operators/fused/
    fused_layernorm_residual_dropout_bias).  The pre-norm residual sum then
    never materializes as its own HBM tensor on the Pallas path
    (ops/pallas_kernels.py fused_ln_residual); the composite lowering adds
    it inline.

    Conditions: the add's output feeds exactly the layer_norm (no other
    readers, not fetched via `keep`, not captured by another block, not
    persistable), shapes match exactly (no broadcasting), default axis."""
    keep = set(keep)
    for block, outside in zip(program.blocks, _outside_reads(program)):
        readers = _reader_counts(block)
        writes, _ = _rw_positions(block)
        adds = {}
        for i, op in enumerate(block.ops):
            if (op.type == "elementwise_add"
                    and op.attrs.get("axis", -1) in (-1,)
                    and len(op.input("X")) == 1 and len(op.input("Y")) == 1):
                xv = block._find_var_recursive(op.input("X")[0])
                yv = block._find_var_recursive(op.input("Y")[0])
                if (xv is not None and yv is not None
                        and xv.shape is not None
                        and tuple(xv.shape) == tuple(yv.shape or ())):
                    adds[op.output("Out")[0]] = (op, i)
        fused_adds = []
        for i, op in enumerate(block.ops):
            if op.type != "layer_norm" or op.inputs.get("Residual"):
                continue
            src = op.input("X")[0]
            add, add_i = adds.get(src, (None, -1))
            # adds keeps the LAST elementwise_add writing each Out name — it
            # must also PRECEDE this layer_norm (a later writer is a
            # different def; fusing across it would normalize the wrong sum)
            if (add is None or add_i >= i or readers.get(src, 0) != 1
                    or src in keep or src in outside):
                continue
            # snapshot semantics: fusing moves the reads of the add's X/Y
            # from the add's position down to the layer_norm's — an op
            # between that writes either input (t = a + b; b += 1; ln(t))
            # or re-writes src makes the LN observe the mutation
            if (_accessed_between(writes, add.input("X")[0], add_i, i)
                    or _accessed_between(writes, add.input("Y")[0], add_i, i)
                    or _accessed_between(writes, src, add_i, i)):
                continue
            v = block._find_var_recursive(src)
            if v is not None and v.persistable:
                continue
            op.inputs["X"] = [add.input("X")[0]]
            op.inputs["Residual"] = [add.input("Y")[0]]
            fused_adds.append(add)
        if fused_adds:
            dead = set(id(a) for a in fused_adds)
            block.ops = [o for o in block.ops if id(o) not in dead]
    program._bump()


@register_pass("fuse_bias_act")
def fuse_bias_act(program, keep=()):
    """Merge `elementwise_add` -> `relu`/`gelu` pairs into
    elementwise_add(fuse_act=<act>) (reference: the fc_fuse / conv+bias+act
    family of ir passes; here the activation folds into the add so the
    Pallas bias-act epilogue — or XLA's own fused maximum/erf chain —
    applies it in the same pass over the activation, ISSUE-17 gap ranking's
    top unfused elementwise pair).

    Safe only when the add's Out is read by exactly that activation and
    nowhere else (any other reader still needs the pre-activation value);
    `keep` names fetch targets that must stay written."""
    keep = set(keep)
    for block, outside in zip(program.blocks, _outside_reads(program)):
        readers = _reader_counts(block)
        writes, reads = _rw_positions(block)
        by_out = {}
        for i, op in enumerate(block.ops):
            if (op.type == "elementwise_add"
                    and not op.attrs.get("fuse_act")
                    and len(op.input("X")) == 1 and len(op.input("Y")) == 1):
                by_out[op.output("Out")[0]] = (op, i)
        kept = []
        for i, op in enumerate(block.ops):
            if op.type in ("relu", "gelu"):
                src = op.input_arg_names[0]
                add, add_i = by_out.get(src, (None, -1))
                # by_out keeps the LAST add writing each Out name — it must
                # also PRECEDE this activation (a later writer is a
                # different def; pairing across it would miscompile)
                if add is not None and add_i >= i:
                    add = None
                out_name = op.output("Out")[0] if add is not None else None
                # snapshot semantics: fusing moves the write of Out from the
                # activation's position up to the add's — any op between
                # that reads Out (old value) or writes Out, or that writes
                # the add's Out (so the activation never saw the add's
                # value), makes the move observable
                hazard = add is not None and (
                    _accessed_between(writes, src, add_i, i)
                    or _accessed_between(writes, out_name, add_i, i)
                    or _accessed_between(reads, out_name, add_i, i))
                if (add is not None and not hazard
                        and readers.get(src, 0) == 1
                        and src not in keep and src not in outside):
                    v = block._find_var_recursive(src)
                    if v is None or not v.persistable:
                        # the add now writes the activation's output var
                        add.outputs["Out"] = [op.output("Out")[0]]
                        add.attrs["fuse_act"] = op.type
                        continue
            kept.append(op)
        block.ops = kept
    program._bump()


@register_pass("prune_dead_ops")
def prune_dead_ops(program, targets: Optional[Sequence[str]] = None):
    """Fetch-driven dead-op elimination as a standalone pass (the executor
    runs the same logic per compile; reference: prune in
    save_inference_model io.py:915).  `targets` is REQUIRED — guessing
    live outputs would silently delete independent branches."""
    from .executor import _CompiledStep, _runnable_ops

    if not targets:
        raise ValueError(
            "prune_dead_ops: pass the fetch targets explicitly "
            "(apply_pass(prog, 'prune_dead_ops', targets=[...]))")
    persistable = {v.name for v in program.list_vars() if v.persistable}
    block = program.global_block()
    block.ops = _CompiledStep._prune(_runnable_ops(block), list(targets), persistable)
    program._bump()
