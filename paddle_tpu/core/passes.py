"""Program-rewrite pass infrastructure.

Reference: framework/ir/ — `ir::Graph` + `Pass` registry + ~60 passes
(fusions, memory opt, multi-device lowering) applied by BuildStrategy.

TPU-first: XLA owns fusion/layout/scheduling, so the reference's kernel-
fusion passes have no residue to produce — the passes that REMAIN useful
are program-level rewrites ahead of lowering: dead-op pruning, identity
elimination, algebraic folds, and structural rewrites (PipelineOptimizer's
stage cut is morally one of these).  The IR the passes walk is the Program
itself (op/var lists) — the redesign collapsed the separate ir::Graph; a
pass is any callable Program -> None mutating in place.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

_PASS_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    def deco(fn):
        _PASS_REGISTRY[name] = fn
        return fn

    return deco


def registered_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


def _verify_after(program, pass_name: str):
    """Pass-safety harness: under FLAGS_verify_program, re-verify the
    program after a rewrite so a pass bug surfaces as an immediate
    diagnostic naming the offending op/var instead of wrong numerics (or
    an opaque trace error) at lowering time."""
    from ..flags import flag

    level = flag("FLAGS_verify_program")
    if level in ("", "off"):
        return
    from .analysis import SEV_ERROR, PassVerificationError, verify_program

    diags = verify_program(program, level=level)
    errors = [d for d in diags if d.severity == SEV_ERROR]
    if errors:
        raise PassVerificationError(pass_name, errors)


def apply_pass(program, name: str, **kw):
    if name not in _PASS_REGISTRY:
        raise KeyError(f"unknown pass {name!r}; known: {registered_passes()}")
    _PASS_REGISTRY[name](program, **kw)
    _verify_after(program, name)
    return program


class PassBuilder:
    """reference core.PassBuilder (build_strategy._finalize surface): an
    ordered pass pipeline."""

    def __init__(self, passes: Optional[Sequence[str]] = None):
        self._passes: List[str] = list(passes or [])

    def append_pass(self, name: str) -> "PassBuilder":
        if name not in _PASS_REGISTRY:
            raise KeyError(f"unknown pass {name!r}")
        self._passes.append(name)
        return self

    def remove_pass(self, name: str) -> "PassBuilder":
        self._passes.remove(name)
        return self

    def all_passes(self) -> List[str]:
        return list(self._passes)

    def apply(self, program):
        """Apply the pipeline; under FLAGS_verify_program each pass is
        followed by a program verification (see `_verify_after`)."""
        for p in self._passes:
            apply_pass(program, p)
        return program


def _rewire(block, old: str, new: str, start: int):
    """Replace reads of `old` with `new` in ops from index `start` on."""
    for op in block.ops[start:]:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [new if n == old else n for n in names]


@register_pass("remove_identity_ops")
def remove_identity_ops(program, keep=()):
    """Drop `assign` and no-op `scale` (scale=1, bias=0) ops, rewiring
    same-block consumers to the producer (reference: identity-elimination
    portion of the inplace/memory passes).

    `keep`: names that must stay written (fetch targets).  Identities whose
    output is kept, persistable, or read from another block (control-flow
    sub-blocks) are conservatively left in place."""
    keep = set(keep)
    # one pre-pass over the whole program: per-block read sets + a global
    # reader count per name, so "is this var read from ANOTHER block"
    # (sub-block capture) is an O(1) lookup instead of an O(blocks^2)
    # rescan of every other block's op list per block
    block_reads = []
    n_blocks_reading: Dict[str, int] = {}
    for b in program.blocks:
        reads = set()
        for op in b.ops:
            reads.update(op.input_arg_names)
        block_reads.append(reads)
        for n in reads:
            n_blocks_reading[n] = n_blocks_reading.get(n, 0) + 1
    for block, my_reads in zip(program.blocks, block_reads):
        def read_outside(n):
            return n_blocks_reading.get(n, 0) > (1 if n in my_reads else 0)
        # var -> index of its LAST write (one pass; keeps the hazard check
        # below O(1) per candidate instead of a tail rescan)
        last_write: Dict[str, int] = {}
        for i, op in enumerate(block.ops):
            for out_name in op.output_arg_names:
                last_write[out_name] = i
        kept = []
        for i, op in enumerate(block.ops):
            is_identity = op.type == "assign" or (
                op.type == "scale"
                and op.attrs.get("scale", 1.0) == 1.0
                and op.attrs.get("bias", 0.0) == 0.0
            )
            if not is_identity:
                kept.append(op)
                continue
            src = op.input_arg_names[0]
            dst = op.output_arg_names[0]
            dst_var = block._find_var_recursive(dst)
            if (dst in keep or read_outside(dst)
                    or (dst_var is not None and dst_var.persistable)):
                kept.append(op)  # fetched / captured / state: not removable
                continue
            # snapshot semantics: if any later op WRITES src or dst, the
            # assign is a real copy (t = x; x += 1; use t) — rewiring reads
            # of dst to src would observe the mutation.  Keep it.
            if last_write.get(src, -1) > i or last_write.get(dst, -1) > i:
                kept.append(op)
                continue
            _rewire(block, dst, src, i + 1)
        block.ops = kept
    program._bump()


@register_pass("fold_scale_chains")
def fold_scale_chains(program):
    """Fold consecutive scale ops (y = a2*(a1*x + b1) + b2) into one
    (reference: the algebraic-simplification family of ir passes).  The
    bypassed intermediate op stays in the program (it may feed other
    consumers or fetches); the executor's compile-time prune drops it when
    genuinely dead."""
    for block in program.blocks:
        by_output = {}
        for op in block.ops:
            if op.type == "scale" and op.attrs.get("bias_after_scale", True):
                src = op.input_arg_names[0]
                prev = by_output.get(src)
                if prev is not None and prev.attrs.get("bias_after_scale", True):
                    a1 = prev.attrs.get("scale", 1.0)
                    b1 = prev.attrs.get("bias", 0.0)
                    a2 = op.attrs.get("scale", 1.0)
                    b2 = op.attrs.get("bias", 0.0)
                    op.inputs["X"] = [prev.input_arg_names[0]]
                    op.attrs["scale"] = a1 * a2
                    op.attrs["bias"] = a2 * b1 + b2
                by_output[op.output_arg_names[0]] = op
            # ANY write invalidates cached chains that read or wrote the
            # same name (in-place ops like increment would otherwise be
            # folded across — wrong numerics)
            for out in op.output_arg_names:
                if op.type != "scale" or out != op.output_arg_names[0]:
                    by_output.pop(out, None)
                stale = [k for k, v in by_output.items()
                         if v.input_arg_names[0] == out and v is not op]
                for k in stale:
                    by_output.pop(k)
    program._bump()


@register_pass("prune_dead_ops")
def prune_dead_ops(program, targets: Optional[Sequence[str]] = None):
    """Fetch-driven dead-op elimination as a standalone pass (the executor
    runs the same logic per compile; reference: prune in
    save_inference_model io.py:915).  `targets` is REQUIRED — guessing
    live outputs would silently delete independent branches."""
    from .executor import _CompiledStep, _runnable_ops

    if not targets:
        raise ValueError(
            "prune_dead_ops: pass the fetch targets explicitly "
            "(apply_pass(prog, 'prune_dead_ops', targets=[...]))")
    persistable = {v.name for v in program.list_vars() if v.persistable}
    block = program.global_block()
    block.ops = _CompiledStep._prune(_runnable_ops(block), list(targets), persistable)
    program._bump()
