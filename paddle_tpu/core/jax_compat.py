"""Version-bridging shims over jax APIs that moved between releases.

The framework tracks the CURRENT jax surface; older jaxlibs still in the
fleet lag behind it.  Each shim prefers the modern spelling and falls back
to the legacy location, so call sites stay written against one API.
"""
from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` (new) / `jax.experimental.shard_map.shard_map` (old).

    The replication-check kwarg was renamed `check_rep` -> `check_vma`
    across the move; this shim accepts the new name and translates.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        except TypeError:
            # transitional releases export jax.shard_map without check_vma
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as legacy_sm

    # The new API leaves mesh axes that no spec mentions to GSPMD (auto);
    # the legacy one maps over EVERY mesh axis, which breaks compositions
    # like a pp-only pipeline on a dp×pp×mp step mesh: with check_rep=False
    # the transpose rule psums cotangents over the unmentioned axes too,
    # silently scaling gradients by their product.  Legacy partial-manual
    # (`auto=`) is not a way out — it aborts in XLA (PartitionId under SPMD
    # partitioning) on these jaxlibs.  Instead, when specs leave axes
    # unmentioned, run fully manual WITH replication checking: inputs
    # gather over the unmentioned axes (redundant compute, same numerics)
    # and the tracked replication makes the transpose exact.
    mentioned = set()
    for spec in jax.tree_util.tree_leaves((in_specs, out_specs)):
        for entry in spec:
            if entry is None:
                continue
            mentioned.update(entry if isinstance(entry, (tuple, list)) else (entry,))
    unmentioned = set(mesh.axis_names) - mentioned
    mapped = legacy_sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_rep=check_vma or bool(unmentioned))
    if not unmentioned:
        return mapped

    # Known legacy-GSPMD miscompile: a value PRODUCED inside the enclosing
    # jit (e.g. jnp.stack of per-stage params) entering a manual region on
    # a multi-axis mesh gets sliced wrongly (devices receive the wrong
    # stage's block).  Pinning every input replicated before the manual
    # region sidesteps the bad full-to-shard; with all axes manual +
    # check_rep this is also what the semantics require.
    from jax.sharding import NamedSharding, PartitionSpec

    repl = NamedSharding(mesh, PartitionSpec())

    def pinned(*args):
        args = jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, repl)
            if hasattr(a, "dtype") else a, args)
        return mapped(*args)

    return pinned
