"""Initializers: append init ops to the startup program.

Reference: python/paddle/fluid/initializer.py (Constant, Uniform, Normal,
TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArrayInitializer).  Same
model: an initializer appends one op writing the parameter into the startup
block; the executor runs the startup program once and the arrays land in the
Scope as device buffers.
"""
from __future__ import annotations

import numpy as np


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            "fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype, "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0, seed: int = 0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            "uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": self.low,
                "max": self.high,
                "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0, seed: int = 0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0, seed: int = 0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


def _fans(var):
    shape = var.shape
    if len(shape) < 2:
        return int(shape[0]), int(shape[0])
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = int(shape[1]) * receptive if len(shape) > 2 else int(shape[0])
    fan_out = int(shape[0]) * receptive if len(shape) > 2 else int(shape[1])
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform: bool = True, fan_in=None, fan_out=None, seed: int = 0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fans(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / (fi + fo)))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform: bool = True, fan_in=None, seed: int = 0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fans(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = float(np.sqrt(6.0 / fi))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / fi))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            "assign_value",
            outputs={"Out": [var.name]},
            attrs={"values": self.value, "dtype": var.dtype, "shape": list(self.value.shape)},
        )


# reference-style aliases (initializer.py exports these names)
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer


class BilinearInitializer(Initializer):
    """reference initializer.py BilinearInitializer: bilinear upsampling
    kernel for conv_transpose weights [c_out, c_in, k, k]."""

    def _value(self, shape, dtype):
        import numpy as np

        # the value depends only on the last two axes: build one k x k tile
        # and broadcast it (O(k^2), not O(prod(shape)))
        kh, kw = shape[-2], shape[-1]
        f = int(np.ceil(kw / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        xs = 1 - np.abs(np.arange(kw) / f - c)
        ys = 1 - np.abs(np.arange(kh) / f - c)
        tile = np.outer(ys, xs).astype("float32")
        return np.broadcast_to(tile, shape).astype(dtype).copy()

    def __call__(self, var, block):
        import numpy as np

        value = self._value(tuple(int(d) for d in var.shape), "float32")
        block.append_op(
            "assign_value",
            outputs={"Out": [var.name]},
            attrs={"shape": list(value.shape), "dtype": "float32",
                   "values": value.reshape(-1).tolist()},
        )


def force_init_on_cpu():
    """reference initializer.force_init_on_cpu: always False here — there
    is no separate CPU init placement under XLA (PJRT owns placement)."""
    return False


class init_on_cpu:
    """reference initializer.init_on_cpu context: accepted no-op (PJRT owns
    placement)."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False
