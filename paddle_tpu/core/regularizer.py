"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py).

`append_regularization_ops` is called by Optimizer.apply_gradients and
appends grad := grad + penalty ops into the main program, exactly like the
reference; XLA fuses them into the update step.
"""
from __future__ import annotations


class Regularizer:
    def append_ops(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(Regularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append_ops(self, param, grad, block):
        decay = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op(
            "scale",
            inputs={"X": [param.name]},
            outputs={"Out": [decay.name]},
            attrs={"scale": self.coeff},
        )
        out = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op(
            "sum",
            inputs={"X": [grad.name, decay.name]},
            outputs={"Out": [out.name]},
        )
        return out


class L1DecayRegularizer(Regularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append_ops(self, param, grad, block):
        sign = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op("sign", inputs={"X": [param.name]}, outputs={"Out": [sign.name]})
        decay = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op(
            "scale", inputs={"X": [sign.name]}, outputs={"Out": [decay.name]}, attrs={"scale": self.coeff}
        )
        out = block.create_var(shape=param.shape, dtype=param.dtype)
        block.append_op("sum", inputs={"X": [grad.name, decay.name]}, outputs={"Out": [out.name]})
        return out


def append_regularization_ops(params_grads, regularization=None):
    out = []
    for param, grad in params_grads:
        regularizer = param.regularizer or regularization
        if regularizer is None:
            out.append((param, grad))
            continue
        new_grad = regularizer.append_ops(param, grad, grad.block)
        out.append((param, new_grad))
    return out


L2Decay = L2DecayRegularizer
L1Decay = L1DecayRegularizer
