"""SelectedRows: the row-slab sparse gradient (reference:
framework/selected_rows.h:32, merge/add kernels in
operators/math/selected_rows_functor.cc).

TPU-first redesign: XLA wants static shapes, so a SelectedRows is a fixed
(N,) `rows` index vector plus (N, D) `values` — duplicates allowed, and
`merged()` (the reference's scatter::MergeAdd) dedups with a sort +
in-batch segment-sum, writing the sentinel row id `height` into freed
duplicate slots so downstream scatters drop them (`mode="drop"`).  A V×D
embedding table under `is_sparse=True` therefore never materializes a
dense V×D gradient: the backward taps the lookup outputs (core/lowering.py)
and the optimizer sparse kernels (ops/optimizer_ops.py) gather/scatter only
the touched rows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.tree_util import register_pytree_node_class


@register_pytree_node_class
class SelectedRows:
    """rows: (N,) int32 row ids (may repeat; entries == height are dropped);
    values: (N, D) per-row gradient slabs; height: table row count V."""

    def __init__(self, rows, values, height: int):
        self.rows = rows
        self.values = values
        self.height = height

    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    def merged(self) -> "SelectedRows":
        """Reference MergeAdd: sum duplicate rows.  Static-shape variant:
        sort by row id, segment-sum runs inside the batch, park freed slots
        at the sentinel id (height)."""
        n = self.rows.shape[0]
        if n == 0:
            return self
        order = jnp.argsort(self.rows)
        r = jnp.take(self.rows, order)
        v = jnp.take(self.values, order, axis=0)
        first = jnp.concatenate([jnp.ones((1,), bool), r[1:] != r[:-1]])
        seg = jnp.cumsum(first) - 1
        summed = jax.ops.segment_sum(v, seg, num_segments=n)
        rows_out = jnp.full((n,), self.height, dtype=r.dtype)
        rows_out = rows_out.at[seg].set(r)
        return SelectedRows(rows_out, summed, self.height)

    def to_dense(self):
        d = jnp.zeros(self.shape, self.values.dtype)
        return d.at[self.rows].add(self.values, mode="drop")

    def __array__(self, dtype=None):
        # dense view for np.asarray consumers (the executor's scope
        # materialization, save_vars): a published full-coverage sparse
        # table serves through the same lookup program as a dense one
        a = np.asarray(self.to_dense())
        return a.astype(dtype) if dtype is not None else a

    def __repr__(self):
        return f"SelectedRows(height={self.height}, nnz={self.rows.shape[0]}, d={self.values.shape[1:]})"
