"""Typed flag registry with FLAGS_* env passthrough.

Reference: gflags end-to-end — C++ DEFINE_* at point of use, Python collects
a whitelist and seeds it from the environment
(`python/paddle/fluid/__init__.py:154-216`), so the public config surface is
`FLAGS_xxx` env vars plus `fluid.set_flags`/`fluid.get_flags`.

TPU build: one registry.  Flags either drive real behavior here (NaN
checking, HLO dumps, compile-cache size) or are accepted no-ops kept for
source compatibility (allocator/cudnn knobs that PJRT/XLA own now — each
says so in its help string)."""
from __future__ import annotations

import os
from typing import Any, Dict, List

_REGISTRY: Dict[str, dict] = {}


def _define(name: str, typ, default, help: str):
    _REGISTRY[name] = {"type": typ, "value": default, "default": default, "help": help}


def DEFINE_bool(name, default, help=""):
    _define(name, bool, default, help)


def DEFINE_int(name, default, help=""):
    _define(name, int, default, help)


def DEFINE_float(name, default, help=""):
    _define(name, float, default, help)


def DEFINE_string(name, default, help=""):
    _define(name, str, default, help)


def _coerce(typ, v):
    if typ is bool:
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes", "on")
        return bool(v)
    return typ(v)


def set_flags(flags: Dict[str, Any]):
    """fluid.set_flags({"FLAGS_check_nan_inf": True})"""
    for k, v in flags.items():
        if k not in _REGISTRY:
            raise KeyError(f"unknown flag {k!r}; known: {sorted(_REGISTRY)}")
        ent = _REGISTRY[k]
        ent["value"] = _coerce(ent["type"], v)
        if k == "FLAGS_xla_dump_to":
            apply_xla_dump()
        elif k == "FLAGS_compile_cache_dir":
            apply_compile_cache()
        elif k in ("FLAGS_lock_telemetry", "FLAGS_lock_timeout_s"):
            from .core import locks as _locks

            _locks.refresh_from_flags()


def get_flags(names) -> Dict[str, Any]:
    if isinstance(names, str):
        names = [names]
    return {n: _REGISTRY[n]["value"] for n in names}


def flag(name: str):
    return _REGISTRY[name]["value"]


def all_flags() -> List[str]:
    return sorted(_REGISTRY)


def init_from_env():
    """Seed every registered flag from its FLAGS_* env var (the reference's
    `core.init_gflags(["--tryfromenv=..."])` role)."""
    for name, ent in _REGISTRY.items():
        if name in os.environ:
            ent["value"] = _coerce(ent["type"], os.environ[name])


# --- the registry -----------------------------------------------------------

DEFINE_bool("FLAGS_check_nan_inf", False,
            "after each run, scan fetched values for NaN/Inf and raise "
            "(reference operator.cc:950 CheckTensorNANOrInf; here a per-fetch "
            "host guard)")
DEFINE_string("FLAGS_xla_dump_to", "",
              "directory for XLA HLO dumps of every compiled program "
              "(reference graphviz/debug dumps); set before first compile")
DEFINE_int("FLAGS_executor_cache_capacity", 128,
           "LRU capacity of the executor's compiled-program cache "
           "(reference use_program_cache)")
DEFINE_string("FLAGS_compile_cache_dir", "",
              "directory for XLA's persistent compilation cache: cold-start "
              "executor.compile cost (seconds per program signature, re-paid "
              "every process) is paid once per machine — the second process "
              "running the same program loads the compiled executable from "
              "disk.  Set before the first compile (env var or set_flags). "
              "Single-process only: init_distributed force-disables it for "
              "multi-process runs (cached cross-process executables corrupt "
              "the heap on the current backend)")
DEFINE_string("FLAGS_fault_spec", "",
              "deterministic fault-injection schedule for chaos testing the "
              "resilience layer (paddle_tpu/faults.py), e.g. "
              "'bad_batch@2;nan@5;device@7:RESOURCE_EXHAUSTED;preempt@11'. "
              "Each resilient_train_loop call builds one injector from the "
              "spec; every entry fires exactly once per injector (so once "
              "per call).  Empty (default) injects nothing")
DEFINE_int("FLAGS_data_corrupt_budget", 0,
           "number of corrupt/truncated RecordIO chunks one run may skip "
           "before the data layer aborts with a classified DataError "
           "(paddle_tpu/recordio.py; `data.corrupt_chunks` counts spends). "
           "0 (default) keeps strict behavior: the first corrupt chunk "
           "raises IOError immediately instead of being skipped")
DEFINE_string("FLAGS_verify_program", "structural",
              "static-analysis level applied to programs BEFORE lowering "
              "(paddle_tpu/core/analysis.py): 'off' trusts the builder "
              "(also disables append_op-time shape/dtype inference — the "
              "escape hatch if an infer rule wrongly rejects a program), "
              "'structural' (default) runs the program verifier "
              "(def-before-use, dangling vars, unregistered ops, orphan "
              "sub-blocks, duplicate parameter writes, feed/fetch targets) "
              "on every executor compile-cache miss and after every "
              "registered pass (PassBuilder/apply_pass), 'full' adds "
              "whole-program shape/dtype re-inference and the hazard lints "
              "(donation aliasing, recompile hazards, collective order, "
              "RNG determinism).  Error-severity findings raise classified "
              "ProgramVerificationError naming the op, var, and block")
DEFINE_string("FLAGS_resource_precheck", "on",
              "static OOM pre-check on every executor compile-cache miss "
              "(paddle_tpu/core/resource_plan.py): 'on' (default) plans the "
              "program's liveness-based peak HBM and raises a classified "
              "ResourceError naming the watermark ops when the plan exceeds "
              "the device limit — BEFORE any XLA compile or allocation; "
              "'off' skips planning entirely.  The limit comes from "
              "FLAGS_resource_hbm_limit_mb when set, else the device's own "
              "memory_stats bytes_limit; with neither known (XLA:CPU "
              "exposes no stats) the check is a no-op")
DEFINE_float("FLAGS_resource_hbm_limit_mb", 0.0,
             "HBM limit (MB) the resource pre-check plans against; 0 "
             "(default) auto-detects from the device's memory_stats.  Set "
             "explicitly to plan for a different chip than the one "
             "attached, or to exercise the over-budget path in tests")
DEFINE_string("FLAGS_feed_validation", "shape",
              "feed-boundary validation level at DataLoader/DataFeeder "
              "(paddle_tpu/reader.py FeedSpec): 'off' trusts the caller, "
              "'shape' (default) checks dtype-kind + shape against the feed "
              "vars and raises DataError naming the slot BEFORE lowering "
              "(a mismatched feed otherwise surfaces as an opaque XLA "
              "error), 'full' additionally scans floating feeds for "
              "NaN/Inf")
DEFINE_float("FLAGS_dist_heartbeat_interval_s", 0.5,
             "seconds between liveness beats each worker publishes to its "
             "peers (paddle_tpu/dist_resilience.py).  The transport rides "
             "the PADDLE_TRAINER_* endpoint contract: UDP to every peer "
             "endpoint, or files under PADDLE_HEARTBEAT_DIR when set "
             "(what paddle_tpu.launch uses on localhost)")
DEFINE_float("FLAGS_dist_heartbeat_miss_factor", 10.0,
             "a peer is declared dead after interval_s * miss_factor "
             "seconds without an observed beat; the collective watchdog "
             "then raises PeerFailureError instead of letting the next "
             "collective hang forever.  Keep the product in whole seconds: "
             "a beat thread can starve behind GIL-heavy import/compile "
             "phases, and a too-tight deadline reads starvation as death")
DEFINE_float("FLAGS_dist_straggler_lag_steps", 1.0,
             "live straggler detection (paddle_tpu/dist_resilience.py): a "
             "rank whose dispatch-attempt count lags the gang by at least "
             "this many steps across 3 consecutive heartbeats is named a "
             "straggler (dist.straggler_suspects counter, "
             "dist.step_skew_frac gauge, one 'straggler' dist_event) "
             "before any watchdog deadline fires.  Sync collectives bound "
             "the observable lag at ~1 (fast ranks block inside the "
             "collective), so 1.0 with the 3-beat hold-down is the "
             "sensitive-but-quiet default; raise it on pipelined meshes "
             "that legitimately run ranks ahead")
DEFINE_float("FLAGS_dist_watchdog_timeout_s", 120.0,
             "deadline armed around every collective/blocking device wait "
             "when the distributed health layer is active; on expiry all "
             "thread stacks are dumped and CollectiveTimeoutError raised")
DEFINE_float("FLAGS_dist_bootstrap_timeout_s", 120.0,
             "deadline on jax.distributed.initialize (the gen_nccl_id "
             "role): a gang whose worker never dials in raises "
             "CollectiveTimeoutError instead of blocking the others at "
             "startup")
DEFINE_bool("FLAGS_use_pallas", False,
            "route hot-kernel lowerings to the hand-fused Pallas TPU "
            "kernels (ops/pallas_kernels.py: LayerNorm+residual, BN "
            "scale/shift/relu epilogue, row-slab Adam, hard-label "
            "softmax-cross-entropy, bias+relu/gelu epilogue; ops/"
            "pallas_attention.py SDPA keeps its own use_pallas_sdpa attr). "
            "OPT-IN: off (default) or a non-TPU backend keeps the XLA "
            "composite for every kernel.  Participates in the executor "
            "compile-cache key, so toggling recompiles instead of reusing "
            "stale executables.  Parity: tests/test_pallas_kernels.py; "
            "device A/B: tools/opbench.py --fused")
DEFINE_float("FLAGS_dp_bucket_mb", 4.0,
             "gradient-bucket size cap (MB) for the backward-overlapped "
             "data-parallel all-reduce (parallel/distributed.py "
             "make_grad_sync, CompiledProgram.with_grad_overlap): grads "
             "are grouped reverse-topologically into buckets of at most "
             "this many bytes and each bucket is all-reduced as soon as "
             "its grads are ready, overlapping communication with the "
             "rest of the backward pass (the DDP bucketing strategy)")
DEFINE_int("FLAGS_serving_max_queue", 256,
           "admission-control bound on the serving runtime's request "
           "queue (paddle_tpu/serving/server.py): a submit() past this "
           "depth is SHED with a classified ServingError(reason="
           "'overload') instead of growing tail latency without bound "
           "(serving.shed counter; perf_report --check "
           "--max-shed-frac gates the rate).  Per-Server override via "
           "Server(max_queue=...)")
DEFINE_float("FLAGS_serving_default_deadline_ms", 0.0,
             "default per-request deadline for serving submits that do "
             "not pass their own deadline_ms: a request still queued when "
             "its deadline expires is cancelled with ServingError(reason="
             "'timeout') and the batch proceeds without it "
             "(serving.timeouts counter).  0 (default) = no deadline")
DEFINE_float("FLAGS_serving_hbm_budget_mb", 0.0,
             "HBM budget for multi-model co-residency in the serving "
             "model registry (paddle_tpu/serving/registry.py): loading a "
             "model past the budget first evicts cold (LRU, non-active) "
             "models, then refuses loudly with ServingError(reason="
             "'hbm_budget') — never OOMs the chip mid-request.  Live "
             "usage rides the monitor/memstats gauges.  0 (default) = "
             "unlimited")
DEFINE_float("FLAGS_serving_quant_atol", 5e-2,
             "accuracy-parity gate for publishing a QUANTIZED model over "
             "its fp32 parent (paddle_tpu/serving/publisher.py): during "
             "the golden smoke the staged low-precision snapshot's "
             "outputs are compared elementwise against the ACTIVE "
             "version's outputs on the same feeds; max |diff| past this "
             "tolerance REJECTS + QUARANTINES the snapshot exactly like "
             "NaN weights (the fp32 parent keeps serving bit-identically)."
             "  Only applies when the staged dir carries a __quant__.json "
             "manifest and an active version exists to compare against")
DEFINE_float("FLAGS_serving_slo_target", 0.99,
             "serving SLO good-fraction target the burn-rate gauges are "
             "computed against (paddle_tpu/serving/server.py): a request "
             "is GOOD when it completes within its deadline (no deadline "
             "= completing at all); burn_rate = bad_frac / (1 - target), "
             "so serving.slo_burn_rate > 1.0 means the server is "
             "spending its error budget faster than the SLO allows.  "
             "Sheds, timeouts, errors, and late completions all burn; "
             "admission-door rejections (bad_request/oversize/"
             "model_missing) are not SLO traffic")
DEFINE_string("FLAGS_serving_buckets", "1,2,4,8,16,32",
              "comma-separated pad-to-bucket batch sizes the serving "
              "runtime compiles (paddle_tpu/serving/batcher.py): a "
              "request batch pads up to the next bucket so a novel size "
              "NEVER triggers an inline recompile — buckets warm at "
              "model load (or in the publisher's pre-swap compile lane) "
              "and steady-state serving must keep executor.recompile "
              "flat (perf_report --check's recompile gate)")
DEFINE_int("FLAGS_integrity_check_period", 0,
           "live silent-corruption sentinel (paddle_tpu/integrity.py): "
           "every PERIOD steps the full parameter + optimizer state is "
           "content-digested, amortized chunk-wise so each step hashes "
           "only ~1/PERIOD of the bytes.  In multi-worker gangs the "
           "digest rides the heartbeat telemetry payload and replicated "
           "dp state must agree bit-exactly across ranks — a divergence "
           "majority-votes the corrupt rank, dumps the flight recorder, "
           "and raises a classified errors.IntegrityError that the "
           "resilient loop recovers from via checkpoint rollback.  0 "
           "(default) disables live digesting entirely: the training "
           "loop pays literally nothing")
DEFINE_bool("FLAGS_integrity_verify_load", True,
            "verify the per-file sha256 + byte-length stamps that "
            "io.save/save_sharded record in their manifests whenever a "
            "checkpoint or model directory is loaded (restore, "
            "load_sharded, load_vars, the serving publish ladder): a "
            "mismatch raises a classified errors.IntegrityError naming "
            "the file instead of silently serving rotted bytes.  "
            "Manifests written before the digests existed (no sha256 "
            "field) load unchecked.  Off trusts the disk — the escape "
            "hatch when re-reading every shard for hashing is too "
            "expensive for a given restore path")
DEFINE_string("FLAGS_ckpt_fallback_dir", "",
              "secondary checkpoint destination (a DIFFERENT filesystem — "
              "local scratch, a second mount) tried when a save to the "
              "primary root fails its storage retries or hits a terminal "
              "EROFS/EACCES (paddle_tpu/checkpoint_manager.py).  A "
              "fallback commit clears degraded mode like a primary one, "
              "and restore() merges both roots' checkpoints into one "
              "newest-first walk.  Single-process managers only "
              "(coordinated gang saves need every rank on one shared "
              "dir).  Empty (default) = no fallback: a failed save "
              "enters degraded mode directly.  The fault injector "
              "exempts paths under this dir — it models a different "
              "device, so an injected ENOSPC/EROFS on the primary must "
              "not also break it")
DEFINE_int("FLAGS_max_ckpt_lag_steps", 0,
           "degraded-mode bound (paddle_tpu/checkpoint_manager.py): the "
           "maximum number of steps training may run past its last "
           "COMMITTED checkpoint while storage is failing.  Saves past "
           "the bound raise a terminal classified errors.StorageError "
           "instead of degrading further — unprotected training cannot "
           "run forever on a dead store.  0 (default) = unbounded "
           "degraded mode (the resilience.ckpt_lag_steps gauge and "
           "storage_degraded events still go loud; gate them with "
           "perf_report --check --max-ckpt-lag-steps)")
DEFINE_bool("FLAGS_lock_telemetry", False,
            "per-lock contention telemetry for every named framework lock "
            "(paddle_tpu/core/locks.py): lock.<name>.acquires/contended/"
            "wait_us/hold_us monitor counters plus lock.order_inversions "
            "when an acquisition inverts the declared ranks.  OPT-IN: off "
            "(default) keeps acquire/release at one branch over the raw "
            "primitive (the monitor-overhead hot-path budget); gate the "
            "measured contention with perf_report --check "
            "--max-lock-wait-frac")
DEFINE_float("FLAGS_lock_timeout_s", 0.0,
             "deadline on every blocking named-lock acquisition "
             "(paddle_tpu/core/locks.py): past it the acquire raises a "
             "classified errors.LockTimeoutError naming the wanted lock "
             "AND every lock the thread holds (with declared ranks) "
             "instead of hanging the worker forever — a deadlock dies "
             "loudly and attributable.  0 (default) = no deadline")
DEFINE_float("FLAGS_ps_timeout_s", 10.0,
             "socket deadline on every parameter-server RPC "
             "(paddle_tpu/param_server.py): connect/send/recv past it "
             "raise a classified TRANSIENT errors.ParamServerError the "
             "KVClient retries with reconnect + backoff instead of "
             "wedging training on a dead pserver forever.  0 = no "
             "deadline (the pre-hardening behavior)")
DEFINE_int("FLAGS_ps_retries", 5,
           "KVClient retry budget per RPC (paddle_tpu/param_server.py): "
           "transient ParamServerErrors (timeout, connection refused/"
           "reset while the supervisor restarts the pserver) retry with "
           "seeded exponential backoff up to this many attempts; pushes "
           "carry per-client sequence numbers so a retried push applies "
           "EXACTLY once server-side.  Exhausting the budget raises the "
           "last error terminal")
DEFINE_int("FLAGS_ps_max_frame_mb", 256,
           "frame-size cap on the pserver wire protocol "
           "(paddle_tpu/param_server.py): a length prefix past the cap "
           "is a corrupt/hostile frame and raises a terminal classified "
           "ParamServerError instead of mallocing unbounded on either "
           "end of the socket")
DEFINE_int("FLAGS_ps_snapshot_every_ops", 256,
           "pserver durability cadence (paddle_tpu/param_server.py): a "
           "full table snapshot commits through the io.py atomic choke "
           "point every N journaled mutating ops; between snapshots the "
           "write-ahead op journal alone replays a crash-restarted "
           "pserver back to bit-identical tables.  0 = journal-only "
           "(snapshot only at stop())")
DEFINE_int("FLAGS_max_host_lag_steps", 0,
           "degraded-mode bound for the host sparse tier "
           "(paddle_tpu/parallel/embedding.py): the maximum number of "
           "consecutive steps training may run hot-shard-only (zero "
           "cold-tail rows, stale host tables) while the pserver is "
           "down.  Past the bound the next lookup raises a TERMINAL "
           "classified errors.ParamServerError — online learning cannot "
           "silently diverge from its cold tail forever.  0 (default) = "
           "unbounded degraded mode (the sparse.host_lag_steps gauge "
           "and host_tier_degraded events still go loud; gate them with "
           "perf_report --check --max-host-lag-steps)")
DEFINE_int("FLAGS_publish_period_steps", 0,
           "online-learning publish cadence (paddle_tpu/resilience.py): "
           "resilient_train_loop calls its publish hook every N steps, "
           "maintaining the serving.publish_staleness_steps gauge "
           "(trained step minus last successfully published step).  A "
           "transient storage failure inside the hook is absorbed "
           "(staleness grows, cadence resumes at the next period); "
           "content failures (quarantined snapshot) propagate.  0 "
           "(default) = no publish hook; gate the staleness with "
           "perf_report --check --max-publish-staleness-steps")
DEFINE_bool("FLAGS_cudnn_deterministic", True,
            "accepted no-op: XLA TPU lowerings are deterministic by default")
DEFINE_float("FLAGS_fraction_of_gpu_memory_to_use", 1.0,
             "accepted no-op: PJRT owns device memory")
DEFINE_string("FLAGS_allocator_strategy", "auto_growth",
              "accepted no-op: PJRT owns allocation")
DEFINE_int("FLAGS_paddle_num_threads", 1,
           "accepted no-op: XLA:CPU threading is runtime-managed")

def apply_xla_dump():
    """Wire FLAGS_xla_dump_to into XLA.  Effective for programs compiled
    after the flag is set (XLA reads XLA_FLAGS at backend init; when the
    backend is already up, per-compile env is still consulted by the
    compiler for dump options)."""
    d = flag("FLAGS_xla_dump_to")
    if d and f"--xla_dump_to={d}" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + f" --xla_dump_to={d}"
        ).strip()


def apply_compile_cache():
    """Wire FLAGS_compile_cache_dir into jax's persistent compilation
    cache.  The min-compile-time floor drops to 0 so every program
    signature is cached — the framework compiles few, large programs, so
    the cache stays small and the cold-start win applies to all of them.
    Effective for programs compiled after the flag is set."""
    d = flag("FLAGS_compile_cache_dir")
    if not d:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


init_from_env()
apply_xla_dump()
apply_compile_cache()
