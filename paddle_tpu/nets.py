"""Composite network helpers (reference: python/paddle/fluid/nets.py —
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""
from __future__ import annotations

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size, pool_stride,
                         pool_padding=0, pool_type="max", global_pooling=False,
                         conv_stride=1, conv_padding=0, conv_dilation=1,
                         conv_groups=1, param_attr=None, bias_attr=None,
                         act=None, use_cudnn=True):
    conv = layers.conv2d(input, num_filters=num_filters, filter_size=filter_size,
                         stride=conv_stride, padding=conv_padding,
                         dilation=conv_dilation, groups=conv_groups,
                         param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.pool2d(conv, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """VGG-style conv block stack + one pool (reference nets.py:163)."""
    tmp = input
    for i, nf in enumerate(conv_num_filter):
        local_act = conv_act if not conv_with_batchnorm else None
        tmp = layers.conv2d(tmp, num_filters=nf, filter_size=conv_filter_size,
                            padding=conv_padding, param_attr=param_attr,
                            act=local_act)
        if conv_with_batchnorm:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if conv_batchnorm_drop_rate:
                tmp = layers.dropout(tmp, dropout_prob=conv_batchnorm_drop_rate)
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    conv = layers.sequence_conv(input, num_filters=num_filters,
                                filter_size=filter_size, param_attr=param_attr,
                                act=act)
    return layers.sequence_pool(conv, pool_type)


def glu(input, dim=-1):
    """Gated linear unit: split in half along dim, a * sigmoid(b)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Attention over [b, T, d] (reference nets.py:404): heads split on the
    feature dim, scaled by the PER-HEAD width, merged back after."""
    d = int(queries.shape[-1])
    if num_heads > 1:
        if d % num_heads:
            raise ValueError(f"d_model {d} not divisible by num_heads {num_heads}")
        hd = d // num_heads

        def split(x):
            b, t = x.shape[0], x.shape[1]
            x = layers.reshape(x, [0, 0, num_heads, hd])
            return layers.transpose(x, [0, 2, 1, 3])  # [b, H, T, hd]

        queries, keys, values = split(queries), split(keys), split(values)
    else:
        hd = d
    scaled_q = layers.scale(queries, scale=float(hd) ** -0.5)
    logits = layers.matmul(scaled_q, keys, transpose_y=True)
    weights = layers.softmax(logits)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    out = layers.matmul(weights, values)
    if num_heads > 1:
        out = layers.transpose(out, [0, 2, 1, 3])
        out = layers.reshape(out, [0, 0, d])
    return out
