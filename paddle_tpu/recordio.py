"""RecordIO: native (C++) chunked CRC-checked record files.

Reference: paddle/fluid/recordio/ (713 LoC C++) + recordio_writer.py.  The
on-disk work — chunk framing, CRC validation, record splitting — runs in
native/recordio.cc (built on first use with g++; plain C ABI via ctypes,
since pybind11 isn't in the image).  Python adds the ndarray serde on top:
`write_arrays` / `read_arrays` store dtype+shape headers per record so a
reader pipeline can stream tensors straight out of a file the way the
reference's create_recordio_file_reader op did.
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

_LIB = None
_LIB_LOCK = threading.Lock()


def _native_dir():
    return os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")


def _lib():
    """Compile-on-first-use (cached .so next to the source)."""
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        src = os.path.join(_native_dir(), "recordio.cc")
        so = os.path.join(_native_dir(), "librecordio.so")
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", so, src],
                check=True, capture_output=True, text=True)
        lib = ctypes.CDLL(so)
        lib.rio_error.restype = ctypes.c_char_p
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.rio_write.restype = ctypes.c_int
        lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_next.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.rio_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32)]
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.slotq_open.restype = ctypes.c_void_p
        lib.slotq_open.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                   ctypes.c_int, ctypes.c_longlong,
                                   ctypes.c_int, ctypes.c_int]
        lib.slotq_nslots.restype = ctypes.c_int
        lib.slotq_nslots.argtypes = [ctypes.c_void_p]
        lib.slotq_slot_info.restype = ctypes.c_int
        lib.slotq_slot_info.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int)]
        lib.slotq_next_batch.restype = ctypes.c_longlong
        lib.slotq_next_batch.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_void_p)]
        lib.slotq_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib


def _check(cond, lib):
    if not cond:
        raise IOError(lib.rio_error().decode() or "recordio: unknown error")


class Writer:
    def __init__(self, path: str, max_chunk_records: int = 1024):
        lib = _lib()
        self._lib = lib
        self._h = lib.rio_writer_open(path.encode(), max_chunk_records)
        _check(self._h, lib)

    def write(self, data: bytes):
        rc = self._lib.rio_write(self._h, data, len(data))
        _check(rc == 0, self._lib)

    def close(self):
        if self._h:
            rc = self._lib.rio_writer_close(self._h)
            self._h = None
            _check(rc == 0, self._lib)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class Scanner:
    def __init__(self, path: str):
        lib = _lib()
        self._lib = lib
        self._h = lib.rio_scanner_open(path.encode())
        _check(self._h, lib)

    def __iter__(self) -> Iterator[bytes]:
        ln = ctypes.c_uint32()
        while True:
            ptr = self._lib.rio_next(self._h, ctypes.byref(ln))
            if not ptr:
                err = self._lib.rio_error()
                if err:
                    raise IOError(err.decode())
                return
            yield ctypes.string_at(ptr, ln.value)

    def close(self):
        if self._h:
            self._lib.rio_scanner_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


# --- ndarray serde on top ---------------------------------------------------

def _pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    """One record = one sample = a tuple of ndarrays (slots)."""
    parts = [struct.pack("<I", len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        dt = a.dtype.str.encode()
        parts.append(struct.pack("<I", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<I", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        raw = a.tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def _unpack_arrays(data: bytes) -> List[np.ndarray]:
    off = 0
    (n,) = struct.unpack_from("<I", data, off)
    off += 4
    out = []
    for _ in range(n):
        (dl,) = struct.unpack_from("<I", data, off)
        off += 4
        dt = np.dtype(data[off:off + dl].decode())
        off += dl
        (nd,) = struct.unpack_from("<I", data, off)
        off += 4
        shape = struct.unpack_from(f"<{nd}q", data, off)
        off += 8 * nd
        (raw_len,) = struct.unpack_from("<Q", data, off)
        off += 8
        out.append(np.frombuffer(data, dt, count=int(np.prod(shape)) if nd else 1,
                                 offset=off).reshape(shape))
        off += raw_len
    return out


def write_arrays(path: str, samples, max_chunk_records: int = 1024):
    """samples: iterable of tuples/lists of ndarrays."""
    n = 0
    with Writer(path, max_chunk_records) as w:
        for sample in samples:
            if isinstance(sample, np.ndarray):
                sample = (sample,)
            w.write(_pack_arrays(sample))
            n += 1
    return n


def read_arrays(path: str) -> Iterator[List[np.ndarray]]:
    with Scanner(path) as s:
        for rec in s:
            yield _unpack_arrays(rec)


def reader_creator(path: str):
    """Decorator-style reader (reference recordio_writer.py contract)."""
    def reader():
        yield from read_arrays(path)

    return reader


class SlotBatchReader:
    """Native multithreaded batch reader (reference data_feed.cc
    MultiSlotInMemoryDataFeed role): C++ worker threads scan + parse the
    recordio slot files and slotq_next_batch memcpy-assembles dense batches
    straight into numpy buffers — the GIL is released for the entire call,
    so parsing overlaps device dispatch.  Requires every sample to repeat
    the first record's per-slot dtype/shape (dense slots); ragged data
    raises and callers fall back to the Python path."""

    def __init__(self, files, batch_size, n_threads=4, drop_last=True):
        lib = _lib()
        self._lib = lib
        arr = (ctypes.c_char_p * len(files))(*[f.encode() for f in files])
        self._h = lib.slotq_open(arr, len(files), batch_size, n_threads,
                                 1 if drop_last else 0)
        if not self._h:
            raise RuntimeError(lib.rio_error().decode())
        self.batch_size = batch_size
        self.slots = []
        n = lib.slotq_nslots(self._h)
        for s in range(n):
            buf = ctypes.create_string_buffer(32)
            shape = (ctypes.c_longlong * 8)()
            nd = ctypes.c_int()
            if lib.slotq_slot_info(self._h, s, buf, 32, shape, ctypes.byref(nd)):
                raise RuntimeError("slotq_slot_info failed")
            dt = np.dtype(buf.value.decode())
            self.slots.append((dt, tuple(int(shape[i]) for i in range(nd.value))))

    def __iter__(self):
        while True:
            bufs = [np.empty((self.batch_size,) + shp, dt)
                    for dt, shp in self.slots]
            ptrs = (ctypes.c_void_p * len(bufs))(
                *[b.ctypes.data_as(ctypes.c_void_p).value for b in bufs])
            rows = self._lib.slotq_next_batch(self._h, ptrs)
            if rows < 0:
                raise RuntimeError(self._lib.rio_error().decode())
            if rows == 0:
                return
            yield [b[:rows] for b in bufs]

    def close(self):
        if self._h:
            self._lib.slotq_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
