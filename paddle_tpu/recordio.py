"""RecordIO: native (C++) chunked CRC-checked record files.

Reference: paddle/fluid/recordio/ (713 LoC C++) + recordio_writer.py.  The
on-disk work — chunk framing, CRC validation, record splitting — runs in
native/recordio.cc (built on first use with g++; plain C ABI via ctypes,
since pybind11 isn't in the image).  Python adds the ndarray serde on top:
`write_arrays` / `read_arrays` store dtype+shape headers per record so a
reader pipeline can stream tensors straight out of a file the way the
reference's create_recordio_file_reader op did.
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import weakref
from typing import Iterator, List, Optional, Sequence

import numpy as np

from .core import locks
from .monitor import MONITOR as _MON

_LIB = None
_LIB_LOCK = locks.named_lock("data.recordio_lib", rank=50)

# --- per-run corrupt-chunk budget -------------------------------------------
# A CRC-failed or truncated chunk is dropped (not fatal) while the total
# stays within FLAGS_data_corrupt_budget; every NEW drop increments the
# `data.corrupt_chunks` counter, and the first drop past the budget raises
# a terminal DataError.  Budget 0 (the default) keeps the historical strict
# behavior: the scanner raises IOError on the first corrupt chunk.
#
# Accounting is a per-source HIGH-WATER MARK, not a cumulative sum of
# drops: a multi-epoch run (or a resume's replay fast-forward) re-scans
# the same corrupt chunk every pass, and re-spending it each time would
# let ONE bad chunk exhaust any budget and kill an otherwise-healthy run.
# A source whose drop count rises past its previous high water (the rot
# spread) spends the delta.

_CORRUPT_LOCK = locks.named_lock("data.corrupt_budget", rank=52)
_CORRUPT_HW: dict = {}  # source key -> max drops observed in one pass
# scanned-chunk accounting uses the SAME high-water scheme: the
# `--max-data-corrupt-frac` gate divides corrupt by scanned, and deduping
# only the numerator would dilute the fraction by epoch count (20 epochs
# over a 30%-rotten file must still read as 0.30, not 0.015)
_SCANNED_HW: dict = {}


def corrupt_budget() -> int:
    from .flags import flag

    return int(flag("FLAGS_data_corrupt_budget"))


def corrupt_spent() -> int:
    """Distinct corrupt chunks charged so far in this run (high-water sum
    across sources — re-reads of the same chunk don't double-count)."""
    with _CORRUPT_LOCK:
        return sum(_CORRUPT_HW.values())


def reset_corrupt_spent():
    """Start a fresh budget window (a new training run).  The resilient
    loop calls this on entry; standalone consumers may too."""
    with _CORRUPT_LOCK:
        _CORRUPT_HW.clear()
        _SCANNED_HW.clear()


def _account_scanned(total_for_source: int, where: str):
    """High-water accounting of `data.chunks_scanned`, mirroring the
    corrupt counter so the corrupt/scanned fraction stays per-distinct-
    chunk regardless of how many epochs re-read the source."""
    if total_for_source <= 0:
        return
    with _CORRUPT_LOCK:
        prev = _SCANNED_HW.get(where, 0)
        if total_for_source <= prev:
            return
        delta = total_for_source - prev
        _SCANNED_HW[where] = total_for_source
    _MON.counter("data.chunks_scanned").inc(delta)


def _spend_corrupt(total_for_source: int, where: str):
    """Report one source's cumulative drop count for its current pass;
    charges only the amount above the source's high water against the
    per-run budget.  Raises a terminal DataError (`.budget_exhausted`)
    once the budget is blown — skipping unbounded amounts of data
    silently is worse than dying."""
    if total_for_source <= 0:
        return
    with _CORRUPT_LOCK:
        prev = _CORRUPT_HW.get(where, 0)
        if total_for_source <= prev:
            return  # same chunks re-dropped on a re-read: already charged
        delta = total_for_source - prev
        _CORRUPT_HW[where] = total_for_source
        spent = sum(_CORRUPT_HW.values())
    _MON.counter("data.corrupt_chunks").inc(delta)
    budget = corrupt_budget()
    if spent > budget:
        from .errors import DataError

        e = DataError(
            f"recordio: corrupt-chunk budget exceeded: {spent} corrupt/"
            f"truncated chunk(s) dropped this run, budget is "
            f"FLAGS_data_corrupt_budget={budget} (last file: {where})",
            phase="loader")
        e.budget_exhausted = True  # the resilient loop must not skip this
        raise e


def _native_dir():
    return os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")


def _lib():
    """Compile-on-first-use (cached .so next to the source)."""
    global _LIB
    with _LIB_LOCK:  # lock-ok: one-shot g++ build of the native library — every caller needs the result before it can proceed, so serializing the compile under the lock IS the design; steady state is a dict hit
        if _LIB is not None:
            return _LIB
        src = os.path.join(_native_dir(), "recordio.cc")
        so = os.path.join(_native_dir(), "librecordio.so")
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", so, src],
                check=True, capture_output=True, text=True)
        lib = ctypes.CDLL(so)
        lib.rio_error.restype = ctypes.c_char_p
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.rio_write.restype = ctypes.c_int
        lib.rio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_next.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.rio_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32)]
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_set_tolerant.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rio_scanner_corrupt_chunks.restype = ctypes.c_longlong
        lib.rio_scanner_corrupt_chunks.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_chunks_seen.restype = ctypes.c_longlong
        lib.rio_scanner_chunks_seen.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_tell.restype = ctypes.c_int
        lib.rio_scanner_tell.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_longlong),
                                         ctypes.POINTER(ctypes.c_longlong)]
        lib.rio_scanner_seek.restype = ctypes.c_int
        lib.rio_scanner_seek.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                         ctypes.c_longlong]
        lib.slotq_open.restype = ctypes.c_void_p
        lib.slotq_open.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                   ctypes.c_int, ctypes.c_longlong,
                                   ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.slotq_corrupt_chunks.restype = ctypes.c_longlong
        lib.slotq_corrupt_chunks.argtypes = [ctypes.c_void_p]
        lib.slotq_chunks_seen.restype = ctypes.c_longlong
        lib.slotq_chunks_seen.argtypes = [ctypes.c_void_p]
        lib.slotq_nslots.restype = ctypes.c_int
        lib.slotq_nslots.argtypes = [ctypes.c_void_p]
        lib.slotq_slot_info.restype = ctypes.c_int
        lib.slotq_slot_info.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int)]
        lib.slotq_next_batch.restype = ctypes.c_longlong
        lib.slotq_next_batch.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_void_p)]
        lib.slotq_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib


def _check(cond, lib):
    if not cond:
        raise IOError(lib.rio_error().decode() or "recordio: unknown error")


class Writer:
    def __init__(self, path: str, max_chunk_records: int = 1024):
        lib = _lib()
        self._lib = lib
        self._h = lib.rio_writer_open(path.encode(), max_chunk_records)
        _check(self._h, lib)

    def write(self, data: bytes):
        rc = self._lib.rio_write(self._h, data, len(data))
        _check(rc == 0, self._lib)

    def close(self):
        if self._h:
            rc = self._lib.rio_writer_close(self._h)
            self._h = None
            _check(rc == 0, self._lib)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class Scanner:
    """Sequential record scanner with corruption tolerance + O(1) seek.

    `tolerant=None` (default) derives tolerance from
    `FLAGS_data_corrupt_budget > 0`: tolerant scanners DROP a CRC-failed
    chunk (and a truncated/frame-broken tail) instead of raising, spending
    the per-run budget (`data.corrupt_chunks` counter; a drop past the
    budget raises a terminal DataError).  Strict scanners keep the
    historical contract: IOError on the first corrupt chunk.

    `tell()`/`seek()` expose the native (chunk ordinal, record index)
    cursor — `state_dict()`/`load_state_dict()` ride them, making a scan
    resumable at the cost of one chunk load, not a dataset re-read.

    The native handle is released by whichever comes first: context-manager
    exit, iterator exhaustion/error, explicit `close()`, or GC (a
    `weakref.finalize`; plain iteration without the context manager used
    to leak the handle)."""

    def __init__(self, path: str, tolerant: Optional[bool] = None):
        lib = _lib()
        self._lib = lib
        self._path = path
        h = lib.rio_scanner_open(path.encode())
        _check(h, lib)
        self._h = h
        self._finalizer = weakref.finalize(self, lib.rio_scanner_close, h)
        self.tolerant = corrupt_budget() > 0 if tolerant is None else bool(tolerant)
        if self.tolerant:
            lib.rio_scanner_set_tolerant(self._h, 1)
        self._corrupt_reported = 0

    @property
    def corrupt_chunks(self) -> int:
        """Chunks this scanner dropped so far (tolerant mode)."""
        if self._h:
            return int(self._lib.rio_scanner_corrupt_chunks(self._h))
        return self._corrupt_reported

    def _settle_corrupt(self):
        """Charge newly dropped chunks against the per-run budget (may
        raise the terminal DataError).  Reports this pass's cumulative
        count; the budget's per-source high water dedupes re-reads.  The
        global lock is only touched when the local count ADVANCED."""
        n = int(self._lib.rio_scanner_corrupt_chunks(self._h))
        if n > self._corrupt_reported:
            self._corrupt_reported = n
            _spend_corrupt(n, self._path)

    def _require_open(self, op: str):
        if self._h is None:
            raise ValueError(
                f"recordio.Scanner.{op}: scanner over {self._path!r} is "
                f"closed (iteration exhaustion/error closes it; open a "
                f"fresh Scanner to rescan)")

    def tell(self):
        """(chunk ordinal, record index) of the next record."""
        self._require_open("tell")
        c, r = ctypes.c_longlong(), ctypes.c_longlong()
        self._lib.rio_scanner_tell(self._h, ctypes.byref(c), ctypes.byref(r))
        return int(c.value), int(r.value)

    def seek(self, chunk: int, record: int = 0):
        """Position so the next record is (chunk, record).  Chunk payloads
        before the target are fseek'd over (header reads only)."""
        self._require_open("seek")
        rc = self._lib.rio_scanner_seek(self._h, chunk, record)
        _check(rc == 0, self._lib)

    def state_dict(self) -> dict:
        c, r = self.tell()
        return {"chunk": c, "record": r}

    def load_state_dict(self, state: dict):
        self.seek(int(state["chunk"]), int(state.get("record", 0)))

    # drop counts only change at chunk boundaries, so the tolerant-mode
    # budget settle runs every SETTLE_EVERY records instead of every one —
    # enforcement lags by at most one stride, the per-record hot path pays
    # no extra FFI call.  EOF/error/close always settle exactly.
    SETTLE_EVERY = 64

    def __iter__(self) -> Iterator[bytes]:
        if self._h is None:
            return  # already closed (a prior pass exhausted it): clean EOF
        ln = ctypes.c_uint32()
        tick = 0
        try:
            while self._h is not None:
                ptr = self._lib.rio_next(self._h, ctypes.byref(ln))
                if not ptr:
                    err = self._lib.rio_error()
                    self._settle_corrupt()
                    if err:
                        raise IOError(err.decode())
                    return
                if self.tolerant:
                    # strict scanners can never advance the counter (a
                    # corrupt chunk raises instead): skip settling entirely
                    tick += 1
                    if tick >= self.SETTLE_EVERY:
                        tick = 0
                        self._settle_corrupt()
                yield ctypes.string_at(ptr, ln.value)
        finally:
            # exhaustion, error, or the consumer walking away (generator
            # GC -> GeneratorExit) all release the native handle
            self.close()

    def close(self):
        if self._h is None:
            return
        h, self._h = self._h, None
        # the finalizer is the single owner of the native close (it fires
        # at most once, whether called here or by GC/interpreter exit —
        # two paths fclosing one handle aborts glibc)
        if self._finalizer.alive:
            self._corrupt_reported = int(
                self._lib.rio_scanner_corrupt_chunks(h))
            _account_scanned(int(self._lib.rio_scanner_chunks_seen(h)),
                             self._path)
            self._finalizer()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


# --- ndarray serde on top ---------------------------------------------------

def _pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    """One record = one sample = a tuple of ndarrays (slots)."""
    parts = [struct.pack("<I", len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        dt = a.dtype.str.encode()
        parts.append(struct.pack("<I", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<I", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        raw = a.tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def _unpack_arrays(data: bytes) -> List[np.ndarray]:
    off = 0
    (n,) = struct.unpack_from("<I", data, off)
    off += 4
    out = []
    for _ in range(n):
        (dl,) = struct.unpack_from("<I", data, off)
        off += 4
        dt = np.dtype(data[off:off + dl].decode())
        off += dl
        (nd,) = struct.unpack_from("<I", data, off)
        off += 4
        shape = struct.unpack_from(f"<{nd}q", data, off)
        off += 8 * nd
        (raw_len,) = struct.unpack_from("<Q", data, off)
        off += 8
        out.append(np.frombuffer(data, dt, count=int(np.prod(shape)) if nd else 1,
                                 offset=off).reshape(shape))
        off += raw_len
    return out


def write_arrays(path: str, samples, max_chunk_records: int = 1024):
    """samples: iterable of tuples/lists of ndarrays."""
    n = 0
    with Writer(path, max_chunk_records) as w:
        for sample in samples:
            if isinstance(sample, np.ndarray):
                sample = (sample,)
            w.write(_pack_arrays(sample))
            n += 1
    return n


def read_arrays(path: str, tolerant: Optional[bool] = None) -> Iterator[List[np.ndarray]]:
    with Scanner(path, tolerant=tolerant) as s:
        for rec in s:
            yield _unpack_arrays(rec)


class RecordIOReader:
    """Decorator-style reader over one RecordIO file that speaks the
    stream-state protocol: `state_dict()` called mid-iteration returns the
    (chunk, record) position of the NEXT sample, and `load_state_dict()`
    makes the next `__call__` resume exactly there — one chunk load, not a
    replay of the file.  One live iterator per instance at a time."""

    def __init__(self, path: str, tolerant: Optional[bool] = None):
        self.path = path
        self.tolerant = tolerant
        self._resume: Optional[dict] = None
        self._live: Optional[dict] = None

    def checkpointable(self) -> bool:
        return True

    def state_dict(self) -> dict:
        if self._live is not None:
            return dict(self._live)
        if self._resume is not None:
            return dict(self._resume)
        return {"chunk": 0, "record": 0}

    def load_state_dict(self, state: dict):
        self._resume = {"chunk": int(state["chunk"]),
                        "record": int(state.get("record", 0))}
        self._live = None

    def __call__(self):
        resume, self._resume = self._resume, None
        s = Scanner(self.path, tolerant=self.tolerant)
        try:
            if resume is not None:
                s.load_state_dict(resume)
                self._live = dict(resume)
            it = iter(s)
            while True:
                try:
                    rec = next(it)
                except StopIteration:
                    return
                c, r = s.tell()  # the record AFTER the one just pulled
                self._live = {"chunk": c, "record": r}
                yield _unpack_arrays(rec)
        finally:
            s.close()


def reader_creator(path: str, tolerant: Optional[bool] = None):
    """Decorator-style reader (reference recordio_writer.py contract).
    The returned object is callable like the historical closure AND
    checkpointable (see RecordIOReader)."""
    return RecordIOReader(path, tolerant=tolerant)


class SlotBatchReader:
    """Native multithreaded batch reader (reference data_feed.cc
    MultiSlotInMemoryDataFeed role): C++ worker threads scan + parse the
    recordio slot files and slotq_next_batch memcpy-assembles dense batches
    straight into numpy buffers — the GIL is released for the entire call,
    so parsing overlaps device dispatch.  Requires every sample to repeat
    the first record's per-slot dtype/shape (dense slots); ragged data
    raises and callers fall back to the Python path."""

    def __init__(self, files, batch_size, n_threads=4, drop_last=True,
                 tolerant: Optional[bool] = None):
        lib = _lib()
        self._lib = lib
        self.files = list(files)
        self.n_threads = n_threads
        self.drop_last = drop_last
        self.tolerant = corrupt_budget() > 0 if tolerant is None else bool(tolerant)
        arr = (ctypes.c_char_p * len(files))(*[f.encode() for f in files])
        h = lib.slotq_open(arr, len(files), batch_size, n_threads,
                           1 if drop_last else 0, 1 if self.tolerant else 0)
        if not h:
            raise RuntimeError(lib.rio_error().decode())
        self._h = h
        self._finalizer = weakref.finalize(self, lib.slotq_close, h)
        self.batch_size = batch_size
        self._corrupt_reported = 0
        self._yielded = 0           # batches handed to the consumer
        self._resume_batches = 0    # batches to fast-forward on next __iter__
        self.slots = []
        n = lib.slotq_nslots(self._h)
        for s in range(n):
            buf = ctypes.create_string_buffer(32)
            shape = (ctypes.c_longlong * 8)()
            nd = ctypes.c_int()
            if lib.slotq_slot_info(self._h, s, buf, 32, shape, ctypes.byref(nd)):
                raise RuntimeError("slotq_slot_info failed")
            dt = np.dtype(buf.value.decode())
            self.slots.append((dt, tuple(int(shape[i]) for i in range(nd.value))))

    # -- stream-state protocol ----------------------------------------------
    def checkpointable(self) -> bool:
        # order is only deterministic when ONE worker drains files FIFO;
        # a multi-threaded queue interleaves files run-to-run
        return self.n_threads == 1

    def state_dict(self) -> dict:
        return {"files": list(self.files), "batches_yielded": self._yielded}

    def load_state_dict(self, state: dict):
        if list(state.get("files", self.files)) != self.files:
            raise ValueError(
                f"SlotBatchReader.load_state_dict: file list changed "
                f"(saved {state.get('files')}, this reader {self.files})")
        self._resume_batches = int(state.get("batches_yielded", 0))

    @property
    def corrupt_chunks(self) -> int:
        if self._h:
            return int(self._lib.slotq_corrupt_chunks(self._h))
        return self._corrupt_reported

    def _settle_corrupt(self):
        n = int(self._lib.slotq_corrupt_chunks(self._h))
        if n > self._corrupt_reported:
            self._corrupt_reported = n
            _spend_corrupt(n, "|".join(self.files))

    def _next_batch(self):
        bufs = [np.empty((self.batch_size,) + shp, dt)
                for dt, shp in self.slots]
        ptrs = (ctypes.c_void_p * len(bufs))(
            *[b.ctypes.data_as(ctypes.c_void_p).value for b in bufs])
        rows = self._lib.slotq_next_batch(self._h, ptrs)
        self._settle_corrupt()
        if rows < 0:
            raise RuntimeError(self._lib.rio_error().decode())
        return None if rows == 0 else [b[:rows] for b in bufs]

    def __iter__(self):
        skip, self._resume_batches = self._resume_batches, 0
        for _ in range(skip):
            # native fast-forward: batches are assembled and discarded
            # without per-sample Python work (the workers already parsed
            # them); O(batches) IO, zero Python-loop cost
            if self._next_batch() is None:
                raise RuntimeError(
                    f"SlotBatchReader: stream exhausted after "
                    f"{self._yielded} batches while fast-forwarding "
                    f"{skip} — the files must replay the same stream")
            self._yielded += 1
        while True:
            out = self._next_batch()
            if out is None:
                return
            self._yielded += 1
            yield out

    def close(self):
        if self._h is None:
            return
        h, self._h = self._h, None
        if self._finalizer.alive:  # single-owner close, same as Scanner
            self._corrupt_reported = int(self._lib.slotq_corrupt_chunks(h))
            _account_scanned(int(self._lib.slotq_chunks_seen(h)),
                             "|".join(self.files))
            self._finalizer()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
