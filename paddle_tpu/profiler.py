"""Profiler (reference: python/paddle/fluid/profiler.py + platform/profiler
RecordEvent/DeviceTracer, SURVEY.md §5.1).

Two layers, mirroring the reference:
  * host-side per-run records: the executor reports (program, wall time,
    cache hit) per `run()`; `stop_profiler` prints the aggregate table the
    reference printed from EventList;
  * device-side: `jax.profiler` traces (xprof) exported to a directory —
    Chrome/perfetto-compatible, the role tools/timeline.py played.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional

import jax

_records = defaultdict(lambda: {"calls": 0, "total_s": 0.0, "max_s": 0.0, "min_s": float("inf")})
_enabled = False
_trace_dir: Optional[str] = None


def is_profiler_enabled() -> bool:
    return _enabled


def record_run(tag: str, seconds: float):
    if not _enabled:
        return
    r = _records[tag]
    r["calls"] += 1
    r["total_s"] += seconds
    r["max_s"] = max(r["max_s"], seconds)
    r["min_s"] = min(r["min_s"], seconds)


def reset_profiler():
    _records.clear()


def start_profiler(state: str = "All", tracer_option: Optional[str] = None,
                   trace_dir: Optional[str] = None):
    """state: CPU | GPU | All (kept for parity; device tracing needs
    trace_dir)."""
    global _enabled, _trace_dir
    _enabled = True
    _trace_dir = trace_dir
    if trace_dir is not None:
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key: str = "total", profile_path: Optional[str] = None):
    global _enabled, _trace_dir
    _enabled = False
    if _trace_dir is not None:
        jax.profiler.stop_trace()
        _trace_dir = None
    table = summary(sorted_key)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(table)
    else:
        print(table)
    return table


def summary(sorted_key: str = "total") -> str:
    keyfn = {
        "total": lambda kv: -kv[1]["total_s"],
        "calls": lambda kv: -kv[1]["calls"],
        "max": lambda kv: -kv[1]["max_s"],
        "min": lambda kv: kv[1]["min_s"],
        "ave": lambda kv: -(kv[1]["total_s"] / max(kv[1]["calls"], 1)),
    }.get(sorted_key, lambda kv: -kv[1]["total_s"])
    lines = [
        f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>10} {'Max(ms)':>10} {'Min(ms)':>10}"
    ]
    for tag, r in sorted(_records.items(), key=keyfn):
        avg = r["total_s"] / max(r["calls"], 1)
        lines.append(
            f"{tag:<40} {r['calls']:>8} {r['total_s']*1e3:>12.3f} {avg*1e3:>10.3f} "
            f"{r['max_s']*1e3:>10.3f} {(0 if r['min_s']==float('inf') else r['min_s'])*1e3:>10.3f}"
        )
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total", profile_path: Optional[str] = None,
             trace_dir: Optional[str] = None):
    """reference: fluid.profiler.profiler context manager (profiler.py:222)."""
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
