"""Profiler facade (reference: python/paddle/fluid/profiler.py + platform/
profiler RecordEvent/DeviceTracer, SURVEY.md §5.1).

This module is now a thin compatibility layer over `paddle_tpu.monitor`,
the framework-wide observability subsystem: start/stop toggle the monitor,
the aggregate table renders the monitor's span stats, and trace export
goes through the monitor's Chrome-trace exporter.  New code should use
`paddle_tpu.monitor` directly (spans, counters, gauges, Prometheus/JSON
exporters, JSONL logging — see docs/observability.md); this surface keeps
reference-era scripts and the round-5 bench tooling working unchanged.

Device-side (xprof) tracing is unchanged: pass `trace_dir` and the jax
profiler writes Chrome/perfetto-compatible traces, the role
tools/timeline.py played.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional

import jax

from .monitor import MONITOR as _MON

_trace_dir: Optional[str] = None
_owns_enable = False  # did start_profiler() turn the monitor on?


def is_profiler_enabled() -> bool:
    return _MON.enabled


def record_run(tag: str, seconds: float):
    _MON.observe(tag, seconds)


def reset_profiler():
    _MON.reset()


def start_profiler(state: str = "All", tracer_option: Optional[str] = None,
                   trace_dir: Optional[str] = None):
    """state: CPU | GPU | All (kept for parity; device tracing needs
    trace_dir)."""
    global _trace_dir, _owns_enable
    _owns_enable = not _MON.enabled
    _MON.enable()
    _trace_dir = trace_dir
    if trace_dir is not None:
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key: str = "total", profile_path: Optional[str] = None):
    global _trace_dir, _owns_enable
    # only turn telemetry off if this facade turned it on: a profiler
    # section inside an always-on monitor.enable() run must not kill the
    # user's step records / counters on exit
    if _owns_enable:
        _MON.disable()
    _owns_enable = False
    if _trace_dir is not None:
        jax.profiler.stop_trace()
        _trace_dir = None
    table = summary(sorted_key)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(table)
    else:
        print(table)
    return table


def summary(sorted_key: str = "total") -> str:
    from .monitor.exporters import summary_table

    return summary_table(_MON, sorted_key)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total", profile_path: Optional[str] = None,
             trace_dir: Optional[str] = None):
    """reference: fluid.profiler.profiler context manager (profiler.py:222)."""
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# --- per-op attribution + Chrome-trace export (tools/timeline.py role) ------


def record_event(name: str, ts: float, seconds: float):
    # `ts` is ignored: callers historically passed perf_counter() values,
    # which would land ~50 years away from the monitor's epoch-based span
    # timestamps in one Chrome trace.  observe() stamps epoch time itself.
    _MON.observe(name, seconds)


def profile_program(program, feed, fetch_list=None, scope=None, place=None,
                    repeat: int = 1):
    """Per-op time attribution (reference: the EventList per-op table the
    C++ profiler printed from RecordEvent around every `op->Run`).

    The compiled path fuses the whole block, so per-op wall times don't
    exist at execution; profiling mode interprets the block op-by-op
    eagerly (each op dispatched + synced separately) — same numbers,
    per-op timing, slower wall clock.  Returns the aggregate table string
    and records events for export_chrome_trace()."""
    import numpy as np
    import jax

    import paddle_tpu as fluid
    from .core.lowering import LoweringContext, lower_one
    from .core.executor import _runnable_ops

    scope = scope if scope is not None else fluid.global_scope()
    block = program.global_block()
    ops = [o for o in _runnable_ops(block) if o.type != "backward"]
    env = {}
    for name in (n for n in scope.var_names() if isinstance(n, str)):
        env[name] = scope.find_var(name)
    for k, v in (feed or {}).items():
        env[k] = jax.numpy.asarray(v)

    per_op = defaultdict(lambda: {"calls": 0, "total_s": 0.0})
    ctx = LoweringContext(jax.random.PRNGKey(0))
    for _ in range(repeat):
        for op in ops:
            if any(n not in env for n in op.input_arg_names):
                # backward-produced grads etc. don't exist in the eager
                # per-op pass; attribute what can run standalone
                continue
            t0 = time.perf_counter()
            lower_one(ctx, op, env)
            for out_name in op.output_arg_names:
                v = env.get(out_name)
                if v is not None and hasattr(v, "block_until_ready"):
                    v.block_until_ready()
            dt = time.perf_counter() - t0
            per_op[op.type]["calls"] += 1
            per_op[op.type]["total_s"] += dt
            record_event(op.type, t0, dt)

    lines = [f"{'Op':<28} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>10}"]
    for t, r in sorted(per_op.items(), key=lambda kv: -kv[1]["total_s"]):
        lines.append(f"{t:<28} {r['calls']:>8} {r['total_s']*1e3:>12.3f} "
                     f"{r['total_s']/r['calls']*1e3:>10.3f}")
    return "\n".join(lines)


def export_chrome_trace(path: str, pid: int = 0, process_name: str = "paddle_tpu"):
    """Write recorded events as Chrome trace JSON (chrome://tracing /
    perfetto), the format tools/timeline.py emitted."""
    from .monitor.exporters import export_chrome_trace as _export

    return _export(_MON, path, pid=pid, process_name=process_name)


def merge_chrome_traces(named_paths, out_path):
    """Merge several processes' traces into one timeline (the reference
    tool's `trainer1=f1,ps=f2` multi-process mode): each input gets its own
    pid lane."""
    from .monitor.exporters import merge_chrome_traces as _merge

    return _merge(named_paths, out_path)


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """reference profiler.cuda_profiler (nvprof hooks): accepted no-op on
    TPU — use profiler() / FLAGS_xla_dump_to for traces."""
    yield
