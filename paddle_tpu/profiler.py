"""Profiler (reference: python/paddle/fluid/profiler.py + platform/profiler
RecordEvent/DeviceTracer, SURVEY.md §5.1).

Two layers, mirroring the reference:
  * host-side per-run records: the executor reports (program, wall time,
    cache hit) per `run()`; `stop_profiler` prints the aggregate table the
    reference printed from EventList;
  * device-side: `jax.profiler` traces (xprof) exported to a directory —
    Chrome/perfetto-compatible, the role tools/timeline.py played.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional

import jax

_records = defaultdict(lambda: {"calls": 0, "total_s": 0.0, "max_s": 0.0, "min_s": float("inf")})
_events: list = []  # (name, ts_us, dur_us) for Chrome-trace export
_enabled = False
_trace_dir: Optional[str] = None


def is_profiler_enabled() -> bool:
    return _enabled


def record_run(tag: str, seconds: float):
    if not _enabled:
        return
    r = _records[tag]
    r["calls"] += 1
    r["total_s"] += seconds
    r["max_s"] = max(r["max_s"], seconds)
    r["min_s"] = min(r["min_s"], seconds)


def reset_profiler():
    _records.clear()
    _events.clear()


def start_profiler(state: str = "All", tracer_option: Optional[str] = None,
                   trace_dir: Optional[str] = None):
    """state: CPU | GPU | All (kept for parity; device tracing needs
    trace_dir)."""
    global _enabled, _trace_dir
    _enabled = True
    _trace_dir = trace_dir
    if trace_dir is not None:
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key: str = "total", profile_path: Optional[str] = None):
    global _enabled, _trace_dir
    _enabled = False
    if _trace_dir is not None:
        jax.profiler.stop_trace()
        _trace_dir = None
    table = summary(sorted_key)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(table)
    else:
        print(table)
    return table


def summary(sorted_key: str = "total") -> str:
    keyfn = {
        "total": lambda kv: -kv[1]["total_s"],
        "calls": lambda kv: -kv[1]["calls"],
        "max": lambda kv: -kv[1]["max_s"],
        "min": lambda kv: kv[1]["min_s"],
        "ave": lambda kv: -(kv[1]["total_s"] / max(kv[1]["calls"], 1)),
    }.get(sorted_key, lambda kv: -kv[1]["total_s"])
    lines = [
        f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>10} {'Max(ms)':>10} {'Min(ms)':>10}"
    ]
    for tag, r in sorted(_records.items(), key=keyfn):
        avg = r["total_s"] / max(r["calls"], 1)
        lines.append(
            f"{tag:<40} {r['calls']:>8} {r['total_s']*1e3:>12.3f} {avg*1e3:>10.3f} "
            f"{r['max_s']*1e3:>10.3f} {(0 if r['min_s']==float('inf') else r['min_s'])*1e3:>10.3f}"
        )
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total", profile_path: Optional[str] = None,
             trace_dir: Optional[str] = None):
    """reference: fluid.profiler.profiler context manager (profiler.py:222)."""
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# --- per-op attribution + Chrome-trace export (tools/timeline.py role) ------

_EVENT_CAP = 200_000


def record_event(name: str, ts: float, seconds: float):
    if _enabled and len(_events) < _EVENT_CAP:
        _events.append((name, ts * 1e6, seconds * 1e6))


def profile_program(program, feed, fetch_list=None, scope=None, place=None,
                    repeat: int = 1):
    """Per-op time attribution (reference: the EventList per-op table the
    C++ profiler printed from RecordEvent around every `op->Run`).

    The compiled path fuses the whole block, so per-op wall times don't
    exist at execution; profiling mode interprets the block op-by-op
    eagerly (each op dispatched + synced separately) — same numbers,
    per-op timing, slower wall clock.  Returns the aggregate table string
    and records events for export_chrome_trace()."""
    import numpy as np
    import jax

    import paddle_tpu as fluid
    from .core.lowering import LoweringContext, lower_one
    from .core.executor import _runnable_ops

    scope = scope if scope is not None else fluid.global_scope()
    block = program.global_block()
    ops = [o for o in _runnable_ops(block) if o.type != "backward"]
    env = {}
    for name in (n for n in scope.var_names() if isinstance(n, str)):
        env[name] = scope.find_var(name)
    for k, v in (feed or {}).items():
        env[k] = jax.numpy.asarray(v)

    per_op = defaultdict(lambda: {"calls": 0, "total_s": 0.0})
    ctx = LoweringContext(jax.random.PRNGKey(0))
    for _ in range(repeat):
        for op in ops:
            if any(n not in env for n in op.input_arg_names):
                # backward-produced grads etc. don't exist in the eager
                # per-op pass; attribute what can run standalone
                continue
            t0 = time.perf_counter()
            lower_one(ctx, op, env)
            for out_name in op.output_arg_names:
                v = env.get(out_name)
                if v is not None and hasattr(v, "block_until_ready"):
                    v.block_until_ready()
            dt = time.perf_counter() - t0
            per_op[op.type]["calls"] += 1
            per_op[op.type]["total_s"] += dt
            record_event(op.type, t0, dt)

    lines = [f"{'Op':<28} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>10}"]
    for t, r in sorted(per_op.items(), key=lambda kv: -kv[1]["total_s"]):
        lines.append(f"{t:<28} {r['calls']:>8} {r['total_s']*1e3:>12.3f} "
                     f"{r['total_s']/r['calls']*1e3:>10.3f}")
    return "\n".join(lines)


def export_chrome_trace(path: str, pid: int = 0, process_name: str = "paddle_tpu"):
    """Write recorded events as Chrome trace JSON (chrome://tracing /
    perfetto), the format tools/timeline.py emitted."""
    import json

    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "args": {"name": process_name}}]
    for name, ts, dur in _events:
        events.append({"name": name, "ph": "X", "pid": pid, "tid": 0,
                       "ts": ts, "dur": dur, "cat": "op"})
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(_events)


def merge_chrome_traces(named_paths, out_path):
    """Merge several processes' traces into one timeline (the reference
    tool's `trainer1=f1,ps=f2` multi-process mode): each input gets its own
    pid lane."""
    import json

    merged = []
    for pid, (name, p) in enumerate(named_paths.items()
                                    if isinstance(named_paths, dict)
                                    else enumerate(named_paths)):
        with open(p) as f:
            doc = json.load(f)
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            merged.append(ev)
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": str(name)}})
    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged}, f)
    return out_path


import contextlib as _contextlib


@_contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """reference profiler.cuda_profiler (nvprof hooks): accepted no-op on
    TPU — use profiler() / FLAGS_xla_dump_to for traces."""
    yield
