"""Gang launcher with restart: the `paddle.distributed.launch` role,
grown a fault-tolerance story.

Promoted from tests/dist_harness.py (which now wraps this module): one
copy of the port allocation, the `PADDLE_TRAINER_*` env contract, and
worker spawning — plus what the test harness never had:

  * **leak-free spawning** — `Gang` is a context manager that always
    kills and reaps every worker on the way out (bounded per-worker join,
    SIGTERM then SIGKILL), so a failed spawn or a raising test body never
    strands live subprocesses;
  * **TOCTOU-free ports** — `allocate_port_block(n)` binds all `n`
    consecutive ports simultaneously before releasing them, retrying on
    `EADDRINUSE` with a fresh base instead of assuming `port+i` is free;
  * **gang restart** — `run_gang` supervises the workers, and when one
    dies (SIGKILL, classified resilience exit, crash) it kills the
    stragglers, clears uncommitted checkpoint debris, and relaunches the
    whole gang on a fresh port block with `PADDLE_RESTART_NUM` bumped —
    workers resume from the last *coordinated* checkpoint
    (`CheckpointManager` rank-0 COMMITTED marker) with `step_offset`
    continuity, so the restarted run's params are bit-identical to an
    uninterrupted one.  A worker driving `resilient_train_loop` over a
    checkpointable data source (ISSUE 5 stream-state protocol) resumes
    its input stream by O(1) seek too: the committed checkpoint's
    RESUME.json sidecar carries the pickled reader state, so a restart
    never replays the dataset to find its place.

  * **elastic gangs** (ISSUE 9) — with `elastic=True` (CLI `--elastic`)
    the relaunch follows capacity: an unclassified death shrinks the
    next incarnation to N−1 (classified 43/44 exits are survivors
    reacting, not lost capacity) and workers resume via the elastic
    checkpoint path (`CheckpointManager` N→M re-sharding + stream-cursor
    repartition, `paddle_tpu/elastic.py`); once the shrunk gang commits
    a fresh checkpoint and capacity returns, the supervisor drains it
    gracefully (SIGTERM → flush → exit 0) and grows back toward
    `--nproc`.  Every resize is a `gang_resize` dist_event gated by
    `perf_report --check --max-gang-resizes`.

The once-per-gang fault ledger (`PADDLE_FAULT_STATE_DIR`, exported per
run_gang call) also covers the data faults `corrupt_chunk@N` /
`truncated_file@N`: a restarted incarnation re-opens its RecordIO files,
and without the ledger the injector would re-corrupt them every
incarnation.

CLI (the reference `python -m paddle.distributed.launch` shape):

    python -m paddle_tpu.launch --nproc 2 --max-restarts 3 \
        [--devices-per-proc 1] [--metrics gang.jsonl] worker.py [args...]

Monitor surface: the launcher process emits `dist.gang_restarts` /
`dist.worker_deaths` counters and one `kind="dist_event"` record per
incident (`action="gang_restart"` / `"worker_death"` / `"gang_failed"`),
written to `--metrics` as JSONL — the file `tools/perf_report.py --check
--max-gang-restarts` gates in CI.

Telemetry plane (ISSUE 8): every incarnation also gets a rank-shared
telemetry directory (`--telemetry-root`, default under the checkpoint
root), exported as `PADDLE_TELEMETRY_DIR`; each worker's `fleet.init`
streams its rank-tagged metrics there and arms the flight recorder, the
supervisor harvests `BLACKBOX.p<rank>.json` dumps into
`INCIDENT.i<k>.json` ledgers across restarts, and `tools/trace_merge.py`
/ `perf_report --postmortem` turn the directory into a merged timeline
with straggler attribution.  See docs/observability.md §Debugging a gang.
"""
from __future__ import annotations

__all__ = ["allocate_port_block", "worker_env", "Gang", "GangResult",
           "run_gang", "run_serving_fleet", "main"]

import argparse
import errno
import os
import random
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from . import faults
from .monitor import MONITOR as _MON

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# dist_resilience's classified exits (peer failure / watchdog timeout);
# labels the `classified` field of incident records.  Restart policy is
# deliberately broader — ANY death restarts, because unclassified exits
# include real restartable cases (a raw SIGKILL, a bootstrap lost to
# machine load) and the once-per-gang fault ledger / max_restarts budget
# bound the damage of relaunching a deterministic crasher.
# EXIT_PEER_FAILURE, EXIT_COLLECTIVE_TIMEOUT, EXIT_INTEGRITY: all three
# are ranks REACTING to a condition the gang restart recovers from (a
# dead peer, a wedged collective, detected silent corruption) — not lost
# capacity, so the elastic supervisor relaunches them at full size
_CLASSIFIED_EXITS = (43, 44, 45)


def allocate_port_block(n: int, tries: int = 64,
                        low: int = 20000, high: int = 50000) -> int:
    """Base port of `n` CONSECUTIVE free TCP ports, verified by binding
    all of them simultaneously (close-then-reuse races shrink to the
    spawn window instead of `n` independent guesses).  The old
    `free_port() + i` scheme was a TOCTOU lottery: any daemon grabbing
    `port+i` between close and worker bind wedged the whole bootstrap
    with EADDRINUSE."""
    rng = random.Random(os.getpid() * 7919 + int(time.time() * 1e3) % 65536)
    last_err: Optional[OSError] = None
    for _ in range(tries):
        base = rng.randrange(low, high - n)
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                socks.append(s)
                s.bind(("127.0.0.1", base + i))
            return base
        except OSError as e:
            if e.errno not in (errno.EADDRINUSE, errno.EACCES):
                raise
            last_err = e
        finally:
            for s in socks:
                s.close()
    raise OSError(
        f"allocate_port_block: no free block of {n} consecutive ports in "
        f"[{low}, {high}) after {tries} tries (last: {last_err})")


def worker_env(rank: int, endpoints: Sequence[str],
               devices_per_proc: int = 1,
               extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Env for one worker under the PADDLE_TRAINER_* contract, on the CPU
    virtual mesh (tests / localhost gangs).  The axon tunnel shim
    monkeypatches jax.distributed for its loopback relay, so workers get a
    clean PYTHONPATH rooted at the repo."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO_ROOT
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices_per_proc}"
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(endpoints)
    env["PADDLE_CURRENT_ENDPOINT"] = endpoints[rank]
    env.update(extra or {})
    return env


class Gang:
    """Spawn-and-always-reap context manager around one gang incarnation.

        with Gang([sys.executable, worker_py], n_procs=2) as gang:
            results = gang.communicate(timeout=600)

    On exit — success, failure, or mid-spawn exception — every live
    worker is killed (SIGTERM, then SIGKILL after `grace_s`) and reaped
    with a bounded join, so no orphan ever sits blocked inside
    jax.distributed.initialize holding its port."""

    def __init__(self, argv: Sequence[str], n_procs: int,
                 devices_per_proc: int = 1,
                 extra_env: Optional[Dict[str, str]] = None,
                 per_rank_env: Optional[Dict[int, Dict[str, str]]] = None,
                 grace_s: float = 3.0):
        self.argv = list(argv)
        self.n_procs = n_procs
        self.devices_per_proc = devices_per_proc
        self.extra_env = dict(extra_env or {})
        self.per_rank_env = {r: dict(e) for r, e in (per_rank_env or {}).items()}
        self.grace_s = grace_s
        self.procs: List[subprocess.Popen] = []
        self._files: List[tuple] = []  # (stdout, stderr) spool per worker
        self.base_port: Optional[int] = None
        self.endpoints: List[str] = []

    def __enter__(self) -> "Gang":
        self.base_port = allocate_port_block(self.n_procs)
        self.endpoints = [f"127.0.0.1:{self.base_port + i}"
                          for i in range(self.n_procs)]
        try:
            for rank in range(self.n_procs):
                extra = dict(self.extra_env)
                extra.update(self.per_rank_env.get(rank, {}))
                env = worker_env(rank, self.endpoints,
                                 self.devices_per_proc, extra)
                # worker output goes to spooled temp FILES, not pipes: a
                # pipe fills at ~64KB and a worker chatty past that (per-
                # step logs, repeated stack dumps) would block in write()
                # while the unsuspecting supervisor reads it as "alive"
                out_f = tempfile.TemporaryFile(mode="w+t")
                err_f = tempfile.TemporaryFile(mode="w+t")
                self._files.append((out_f, err_f))
                self.procs.append(subprocess.Popen(
                    self.argv, stdout=out_f, stderr=err_f, env=env,
                    text=True))
        except BaseException:
            self._reap()
            raise
        return self

    def __exit__(self, *exc):
        self._reap()
        return False

    def _reap(self):
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + self.grace_s
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=self.grace_s)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (D-state); nothing more a user can do
        for of, ef in self._files:
            for f in (of, ef):
                try:
                    f.close()
                except OSError:
                    pass
        self._files = []

    def communicate(self, timeout: float = 600):
        """Wait for every worker and read its spooled output; returns
        [(returncode, stdout, stderr)].  Re-callable: the spools are
        seeked, not drained."""
        out = []
        for p, (of, ef) in zip(self.procs, self._files):
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=self.grace_s)
                except subprocess.TimeoutExpired:
                    pass
            o = e = ""
            for f, slot in ((of, "o"), (ef, "e")):
                try:
                    f.seek(0)
                    text = f.read()
                except (OSError, ValueError):
                    text = ""
                if slot == "o":
                    o = text
                else:
                    e = text
            out.append((p.returncode, o, e))
        return out

    def wait_any_death_or_exit(self, poll_s: float = 0.1,
                               timeout: float = 600):
        """Block until every worker exited cleanly, or any worker died
        (non-zero / signaled) — whichever first.  Returns (ok, ranks_done)
        where ok=False names a failed incarnation."""
        t0 = time.monotonic()
        while True:
            codes = [p.poll() for p in self.procs]
            if any(c not in (None, 0) for c in codes):
                return False, codes
            if all(c == 0 for c in codes):
                return True, codes
            if time.monotonic() - t0 > timeout:
                raise TimeoutError(
                    f"gang did not finish within {timeout}s (exit codes so "
                    f"far: {codes}) — watchdogs should have fired long ago")
            time.sleep(poll_s)


@dataclass
class GangResult:
    """What `run_gang` hands back."""

    ok: bool = False
    restarts: int = 0
    incarnations: int = 0
    # last incarnation's per-rank (returncode, stdout, stderr)
    workers: List[tuple] = field(default_factory=list)
    # one dict per death the supervisor observed across all incarnations
    incidents: List[dict] = field(default_factory=list)
    # telemetry root: one i<k> dir per incarnation holding each rank's
    # metrics.p<rank>.jsonl / BLACKBOX.p<rank>.json / trace.p<rank>.json,
    # plus the supervisor's INCIDENT.i<k>.json files — the input of
    # tools/trace_merge.py and perf_report --postmortem
    telemetry_dir: Optional[str] = None
    # elastic supervision (ISSUE 9): world-size changes across the run
    resizes: int = 0
    # one dict per resize: {"direction", "from_nprocs", "to_nprocs", ...}
    resize_events: List[dict] = field(default_factory=list)
    # gang size of each incarnation, in order (e.g. [2, 1, 2] for an
    # N -> N-1 -> N cycle)
    size_history: List[int] = field(default_factory=list)
    final_nprocs: int = 0
    # every incarnation's per-rank (returncode, stdout, stderr) — the
    # last entry aliases `workers`; elastic accounting (which steps each
    # incarnation actually trained) needs the full history
    history: List[List[tuple]] = field(default_factory=list)


def _latest_commit_step(checkpoint_root: Optional[str]) -> int:
    """Step of the newest COMMITTED checkpoint under `checkpoint_root`
    (-1 when none): the elastic supervisor's progress probe — growth only
    interrupts a shrunk gang once it has durably committed something, so
    a resize can never lose more work than a plain restart would."""
    if not checkpoint_root or not os.path.isdir(checkpoint_root):
        return -1
    best = -1
    for name in os.listdir(checkpoint_root):
        if not name.startswith("ckpt-") or name.endswith(".tmp"):
            continue
        if not os.path.exists(os.path.join(checkpoint_root, name,
                                           "COMMITTED")):
            continue
        try:
            best = max(best, int(name[len("ckpt-"):]))
        except ValueError:
            continue
    return best


def _clear_uncommitted(checkpoint_root: str):
    """Drop half-written checkpoint debris (.tmp dirs, stale shard/commit
    markers from the dead incarnation) so the restarted gang's saves can
    never rendezvous with a ghost's markers."""
    if not checkpoint_root or not os.path.isdir(checkpoint_root):
        return
    for name in os.listdir(checkpoint_root):
        if name.endswith(".tmp"):
            shutil.rmtree(os.path.join(checkpoint_root, name),
                          ignore_errors=True)


def run_gang(argv: Sequence[str], n_procs: int, *,
             devices_per_proc: int = 1,
             extra_env: Optional[Dict[str, str]] = None,
             max_restarts: int = 2,
             checkpoint_root: Optional[str] = None,
             heartbeat_dir: Optional[str] = None,
             telemetry_root: Optional[str] = None,
             timeout: float = 600,
             grace_s: float = 3.0,
             peer_grace_s: float = 15.0,
             elastic: bool = False,
             min_procs: int = 1,
             capacity_fn=None,
             log: bool = True) -> GangResult:
    """Supervise `n_procs` copies of `argv` with gang-restart semantics.

    Each incarnation gets a fresh port block and a fresh heartbeat
    directory (a dead incarnation's beats must not fake liveness into the
    next), plus `PADDLE_RESTART_NUM=<k>` so workers know they are a
    resume.  When any worker dies, every straggler is killed and reaped
    (they are wedged or about to classify-exit anyway), uncommitted
    checkpoint debris is cleared, and the gang relaunches — workers
    restore the last COMMITTED coordinated checkpoint and continue with
    global step numbering.  After `max_restarts` exhausted the last
    incarnation's outputs come back with ok=False.

    Elastic mode (ISSUE 9, `elastic=True`): the relaunch after a death
    follows CAPACITY instead of always reusing `n_procs`.

      * **shrink-on-death**: each unclassified death (SIGKILL, crash —
        NOT the classified 43/44 exits, which are survivors REACTING to a
        peer's death and relaunchable on the same host) is lost capacity;
        the next incarnation runs at `max(min_procs, cur - lost)` workers.
        Workers restore the last COMMITTED checkpoint elastically
        (CheckpointManager N->M re-sharding + cursor repartition) and the
        run CONTINUES at reduced size within the same grace window a
        fixed-size restart would need — never a same-size relaunch into
        the missing capacity.
      * **grow-on-capacity**: while running below `n_procs`, the
        supervisor watches for (a) a NEW committed checkpoint — proof the
        shrunk gang made durable progress, so growing cannot lose more
        work than a restart — and (b) available capacity
        (`capacity_fn()`, default: the target size, i.e. capacity returns
        as soon as the shrunk gang commits).  Both true -> the gang is
        drained gracefully (SIGTERM -> each worker's resilient loop
        flushes a coordinated checkpoint and exits 0) and relaunched at
        `min(n_procs, capacity)`.  Grows spend no restart budget.

    Every resize emits a `kind="dist_event" action="gang_resize"` record
    and bumps `dist.gang_resizes` (gated by `perf_report --check
    --max-gang-resizes`); `GangResult.size_history` / `resize_events` /
    `history` carry the full ledger."""
    result = GangResult()
    base_env = dict(extra_env or {})
    if checkpoint_root:
        base_env["PADDLE_CHECKPOINT_ROOT"] = checkpoint_root
    # once-per-gang fault ledger: ranked FLAGS_fault_spec entries
    # (kill_worker/stall_worker) record their firing here so a restarted
    # incarnation replaying the same step does not replay the fault
    if "PADDLE_FAULT_STATE_DIR" not in base_env:
        base_env["PADDLE_FAULT_STATE_DIR"] = (
            os.path.join(checkpoint_root, "fault-state") if checkpoint_root
            else tempfile.mkdtemp(prefix="pt-fault-state-"))
    os.makedirs(base_env["PADDLE_FAULT_STATE_DIR"], exist_ok=True)
    # ledger hygiene (ISSUE 20): a reused checkpoint_root keeps the
    # previous (now dead) gang's fired-* markers, which would wrongly
    # suppress this run's faults; aborted runs also leak one
    # pt-fault-state-* tempdir each.  Sweep dead-PID state here, at run
    # START only — between incarnations a SIGKILLed child's marker has a
    # dead PID by design and must keep suppressing its entry.
    faults.sweep_stale_ledgers(base_env["PADDLE_FAULT_STATE_DIR"])
    # telemetry plane (ISSUE 8): one rank-shared directory per incarnation;
    # workers (fleet.init -> monitor.init_worker_telemetry) stream their
    # rank-stamped metrics there and dump BLACKBOX.p<rank>.json on death.
    # Incarnation dirs are never cleared — a post-mortem wants the history.
    if telemetry_root is None:
        telemetry_root = (os.path.join(checkpoint_root, "telemetry")
                          if checkpoint_root
                          else tempfile.mkdtemp(prefix="pt-telemetry-"))
    os.makedirs(telemetry_root, exist_ok=True)
    result.telemetry_dir = telemetry_root
    target = int(n_procs)
    min_procs = max(1, int(min_procs))
    cur = target
    restarts_left = int(max_restarts)
    incarnation = 0
    while True:
        result.incarnations = incarnation + 1
        result.size_history.append(cur)
        env = dict(base_env)
        env["PADDLE_RESTART_NUM"] = str(incarnation)
        inc_tel = os.path.join(telemetry_root, f"i{incarnation}")
        env["PADDLE_TELEMETRY_DIR"] = inc_tel
        hb = heartbeat_dir or (checkpoint_root and
                               os.path.join(checkpoint_root, "hb"))
        if hb:
            inc_dir = os.path.join(hb, f"i{incarnation}")
            shutil.rmtree(inc_dir, ignore_errors=True)
            env["PADDLE_HEARTBEAT_DIR"] = inc_dir
        grow_to = None
        with Gang(argv, cur, devices_per_proc=devices_per_proc,
                  extra_env=env, grace_s=grace_s) as gang:
            # progress baseline for the grow decision: only a commit made
            # by THIS (shrunk) incarnation proves it is safe to interrupt
            commit_baseline = _latest_commit_step(checkpoint_root) \
                if elastic else None
            t0 = time.monotonic()
            ok = False
            while True:
                codes = [p.poll() for p in gang.procs]
                if any(c not in (None, 0) for c in codes):
                    ok = False
                    break
                if all(c == 0 for c in codes):
                    ok = True
                    break
                if time.monotonic() - t0 > timeout:
                    ok = False
                    break
                if (elastic and grow_to is None and cur < target
                        and checkpoint_root
                        and _latest_commit_step(checkpoint_root)
                        > commit_baseline):
                    try:
                        cap = int((capacity_fn or (lambda: target))())
                    except Exception:
                        cap = target
                    want = min(target, max(cur, cap))
                    if want > cur:
                        # capacity is back and the shrunk gang has durable
                        # progress: drain it gracefully (SIGTERM -> each
                        # worker flushes a coordinated checkpoint and
                        # exits 0) and relaunch at the grown size
                        grow_to = want
                        for p in gang.procs:
                            if p.poll() is None:
                                p.terminate()
                        if log:
                            print(f"paddle_tpu.launch: capacity returned — "
                                  f"draining the {cur}-worker gang to grow "
                                  f"back to {grow_to}",
                                  file=sys.stderr, flush=True)
                time.sleep(0.05)
            if not ok:
                # survivors are raising classified errors right now (their
                # watchdogs see the dead peer); give them one bounded
                # window to exit 43/44 on their own — the exit codes are
                # the incident record — before the reaper kills the rest
                deadline = time.monotonic() + peer_grace_s
                while (time.monotonic() < deadline
                       and any(p.poll() is None for p in gang.procs)):
                    time.sleep(0.05)
                codes = [p.poll() for p in gang.procs]
            result.workers = gang.communicate(timeout=grace_s)
            result.history.append(result.workers)
        if ok and grow_to is None:
            result.ok = True
            result.final_nprocs = cur
            return result
        if ok and grow_to is not None:
            # clean drain: every worker flushed and exited 0 — relaunch
            # bigger.  Spends no restart budget (nothing failed).
            resize = {"kind": "dist_event", "action": "gang_resize",
                      "direction": "grow", "from_nprocs": cur,
                      "to_nprocs": grow_to, "incarnation": incarnation + 1}
            result.resizes += 1
            result.resize_events.append(resize)
            _MON.counter("dist.gang_resizes").inc()
            _MON.record_step(resize)
            if log:
                print(f"paddle_tpu.launch: gang grown {cur} -> {grow_to} "
                      f"workers (resumed from the drain checkpoint)",
                      file=sys.stderr, flush=True)
            cur = grow_to
            incarnation += 1
            continue
        dead = [(r, c) for r, c in enumerate(codes) if c not in (None, 0)]
        incident = {
            "kind": "dist_event", "action": "worker_death",
            "incarnation": incarnation, "nprocs": cur,
            "dead": [{"rank": r, "returncode": c,
                      "classified": c in _CLASSIFIED_EXITS,
                      "signaled": (c is not None and c < 0)}
                     for r, c in dead],
            # per-worker stderr tails: the only forensic record of an
            # incarnation that is about to be replaced
            "stderr_tails": {r: (result.workers[r][2] or "")[-2000:]
                             for r in range(len(result.workers))},
        }
        # harvest the incarnation's black boxes: every rank that managed a
        # flight-recorder dump (injected kill, classified exit, crash hook)
        # left BLACKBOX.p<rank>.json in its telemetry dir; the supervisor
        # records the paths next to the death so `perf_report --postmortem
        # <telemetry_root>` can merge them across restarts
        try:
            incident["blackboxes"] = sorted(
                os.path.join(inc_tel, f) for f in os.listdir(inc_tel)
                if f.startswith("BLACKBOX.p") and f.endswith(".json"))
        except OSError:
            incident["blackboxes"] = []
        try:
            import json as _json

            with open(os.path.join(telemetry_root,
                                   f"INCIDENT.i{incarnation}.json"),
                      "w") as f:
                _json.dump(incident, f, indent=1)
        except OSError:
            pass
        result.incidents.append(incident)
        _MON.counter("dist.worker_deaths").inc(max(len(dead), 1))
        _MON.record_step(incident)
        if log:
            for r, c in dead:
                err = result.workers[r][2] if r < len(result.workers) else ""
                print(f"paddle_tpu.launch: worker {r} died "
                      f"(returncode {c}) in incarnation {incarnation}:\n"
                      f"{(err or '')[-2000:]}", file=sys.stderr, flush=True)
        if restarts_left == 0:
            break
        _clear_uncommitted(checkpoint_root or "")
        nxt = cur
        if elastic:
            # classified 43/44 exits are survivors REACTING to a peer's
            # death — relaunchable on the same host; only unclassified
            # deaths (SIGKILL, crash, a never-exiting straggler) are
            # capacity that actually left
            lost = sum(1 for _r, c in dead if c not in _CLASSIFIED_EXITS)
            if lost:
                nxt = max(min_procs, cur - lost)
        if nxt != cur:
            resize = {"kind": "dist_event", "action": "gang_resize",
                      "direction": "shrink", "from_nprocs": cur,
                      "to_nprocs": nxt, "incarnation": incarnation + 1,
                      "after_death_of": [r for r, _ in dead]}
            result.resizes += 1
            result.resize_events.append(resize)
            _MON.counter("dist.gang_resizes").inc()
            _MON.record_step(resize)
        restarts_left -= 1
        result.restarts += 1
        _MON.counter("dist.gang_restarts").inc()
        _MON.record_step({"kind": "dist_event", "action": "gang_restart",
                          "incarnation": incarnation + 1,
                          "nprocs": nxt,
                          "after_death_of": [r for r, _ in dead]})
        if log:
            what = (f"continuing at {nxt} workers (elastic shrink)"
                    if nxt != cur else f"relaunching {nxt} workers")
            print(f"paddle_tpu.launch: gang restart "
                  f"{result.restarts}/{max_restarts} — {what} from the "
                  f"last coordinated checkpoint",
                  file=sys.stderr, flush=True)
        cur = nxt
        incarnation += 1
    _MON.record_step({"kind": "dist_event", "action": "gang_failed",
                      "restarts": result.restarts})
    result.final_nprocs = cur
    return result


def run_serving_fleet(models: Dict[str, str], n_replicas: int = 2,
                      root: Optional[str] = None,
                      until=None, poll_s: float = 0.5, **fleet_kw) -> dict:
    """Serving-mode supervision (ISSUE 18): run a `ServingFleet` of
    `n_replicas` replica servers until SIGTERM/SIGINT (or the optional
    `until()` predicate turns true), then DRAIN — each replica gets
    SIGTERM, flips its beat to draining so the router stops dispatching,
    serves out its in-flight requests and exits 0.  An interrupted
    rolling publish found persisted in the fleet root is resumed (or
    converged back) before traffic supervision begins — the serving
    analogue of run_gang's restart-from-checkpoint recovery.

    Returns the final router ledger (`Router.stats()`)."""
    import signal as _signal
    import threading as _threading

    from .serving.fleet import ServingFleet

    stop = _threading.Event()
    prev = {}

    def _handler(sig, _frm):
        stop.set()

    for sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            prev[sig] = _signal.signal(sig, _handler)
        except ValueError:
            pass  # not the main thread: caller owns signal wiring
    fleet = ServingFleet(models, n_replicas=n_replicas, root=root,
                         **fleet_kw)
    try:
        fleet.resume_roll()
        fleet.wait_healthy(min_replicas=1)
        while not stop.wait(poll_s):
            if until is not None and until():
                break
    finally:
        fleet.stop()
        for sig, h in prev.items():
            try:
                _signal.signal(sig, h)
            except ValueError:
                pass
    return fleet.stats()


def _serve_main(argv: List[str]) -> int:
    """`python -m paddle_tpu.launch --serve` — fleet CLI."""
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.launch --serve",
        description="Run a supervised serving fleet (replica servers + "
                    "health-aware router + rolling publish) until "
                    "SIGTERM, then drain.")
    ap.add_argument("--serve", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--model", action="append", default=[],
                    metavar="NAME=DIR", required=True,
                    help="model to serve (repeatable)")
    ap.add_argument("--nproc", type=int, default=2,
                    help="replica processes in the fleet")
    ap.add_argument("--fleet-root", default=None,
                    help="fleet state root (hb/, telemetry/, ACTIVE.json, "
                         "ROLL.json; default: a temp dir)")
    ap.add_argument("--buckets", default="1,4,8")
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="per-replica restart budget")
    ap.add_argument("--hb-interval", type=float, default=0.5)
    ns = ap.parse_args(argv)

    models = {}
    for spec in ns.model:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            ap.error(f"--model wants NAME=DIR, got {spec!r}")
        models[name] = path
    from .serving.batcher import parse_buckets

    ledger = run_serving_fleet(
        models, n_replicas=ns.nproc, root=ns.fleet_root,
        buckets=parse_buckets(ns.buckets),
        max_restarts=ns.max_restarts, hb_interval_s=ns.hb_interval)
    print(f"paddle_tpu.launch --serve: drained; "
          f"{ledger['completed']}/{ledger['requests']} completed, "
          f"{ledger['errors']} classified errors", file=sys.stderr)
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if "--serve" in args:
        return _serve_main(args)
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.launch",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--nproc", type=int, default=2,
                    help="workers in the gang (PADDLE_TRAINERS_NUM role)")
    ap.add_argument("--devices-per-proc", type=int, default=1)
    ap.add_argument("--max-restarts", type=int, default=2)
    ap.add_argument("--elastic", action="store_true",
                    help="elastic supervision: continue at N-1 workers "
                         "after an unclassified death (instead of a "
                         "same-size relaunch) and grow back toward "
                         "--nproc once the shrunk gang commits a "
                         "checkpoint and capacity returns")
    ap.add_argument("--min-procs", type=int, default=1,
                    help="elastic floor: never shrink below this many "
                         "workers")
    ap.add_argument("--checkpoint-root", default=None,
                    help="coordinated-checkpoint directory (also exported "
                         "as PADDLE_CHECKPOINT_ROOT to workers)")
    ap.add_argument("--timeout", type=float, default=600)
    ap.add_argument("--telemetry-root", default=None,
                    help="gang telemetry root (per-incarnation worker "
                         "metrics/blackbox/trace dirs; default: "
                         "<checkpoint-root>/telemetry or a temp dir) — the "
                         "input of tools/trace_merge.py and perf_report "
                         "--postmortem")
    ap.add_argument("--metrics", default=None,
                    help="JSONL file for the launcher's dist_event records "
                         "+ final counter snapshot (perf_report --check "
                         "--max-gang-restarts input)")
    ap.add_argument("script", help="worker script")
    ap.add_argument("args", nargs=argparse.REMAINDER)
    ns = ap.parse_args(args)

    logger = None
    if ns.metrics:
        from . import monitor as _monitor
        from .monitor import MonitorLogger

        _monitor.enable()
        logger = _monitor.get_monitor().attach_logger(MonitorLogger(ns.metrics))
    res = run_gang([sys.executable, ns.script, *ns.args], ns.nproc,
                   devices_per_proc=ns.devices_per_proc,
                   max_restarts=ns.max_restarts,
                   checkpoint_root=ns.checkpoint_root,
                   telemetry_root=ns.telemetry_root,
                   timeout=ns.timeout,
                   elastic=ns.elastic, min_procs=ns.min_procs)
    for rank, (code, out, err) in enumerate(res.workers):
        sys.stdout.write(out or "")
        if code != 0:
            sys.stderr.write(f"-- worker {rank} (exit {code}) stderr tail --\n"
                             f"{(err or '')[-2000:]}\n")
    if logger is not None:
        logger.write_snapshot()
        from . import monitor as _monitor

        _monitor.get_monitor().detach_logger(logger)
    sizes = (f", sizes {res.size_history} ({res.resizes} resize(s))"
             if res.resizes else "")
    print(f"paddle_tpu.launch: {'ok' if res.ok else 'FAILED'} after "
          f"{res.incarnations} incarnation(s), {res.restarts} restart(s)"
          f"{sizes}; telemetry in {res.telemetry_dir}",
          file=sys.stderr)
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
