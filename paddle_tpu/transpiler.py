"""DistributeTranspiler source-compatibility layer.

Reference: transpiler/distribute_transpiler.py (DistributeTranspiler:183,
transpile:377) with three modes — pserver (default), nccl2:261,
collective:313 — rewriting programs into send/recv or c_allreduce graphs.

TPU-first mapping (SURVEY §2c):
  * collective / nccl2 modes -> NO program rewrite is needed: the executor
    emits ONE SPMD program whose gradient all-reduces GSPMD inserts.
    `transpile()` therefore only performs the bootstrap the `gen_nccl_id`
    op did (coordination service via parallel/distributed.py) and
    `get_trainer_program()` hands back the program compiled for the global
    mesh.
  * pserver mode for DENSE parameters is intentionally NOT implemented:
    allreduce strictly wins on ICI (SURVEY §2c) — `get_pserver_program`
    raises with that rationale.  The capability the pserver mode actually
    carried (sparse embedding tables) lives in the SelectedRows/ep path
    (core/selected_rows.py, parallel/embedding.py).
"""
from __future__ import annotations

from typing import Optional


class DistributeTranspilerConfig:
    """reference transpiler config: carriers kept for source compat."""

    def __init__(self):
        self.slice_var_up = True
        self.min_block_size = 8192
        self.mode = "collective"  # "nccl2" and "collective" behave the same
        self.sync_mode = True
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False  # ICI handles hierarchy


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._program = None

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None, current_endpoint=""):
        """collective/nccl2 semantics: bootstrap the process group and
        remember the program; no graph rewrite is required on TPU."""
        from .core.program import default_main_program

        # a non-empty `pservers` list IS the legacy pserver-mode request,
        # whatever the config says — fail with the rationale instead of
        # silently training unsynchronized replicas
        if pservers or self.config.mode == "pserver":
            raise NotImplementedError(
                "DistributeTranspiler: dense parameter-server mode is "
                "deliberately unimplemented on TPU — synchronous allreduce "
                "over ICI strictly dominates (SURVEY §2c); use the default "
                "collective mode. Sparse/giant tables: use is_sparse "
                "embeddings (SelectedRows) with ep-axis sharding instead.")
        self._program = program if program is not None else default_main_program()
        if isinstance(trainers, str) and trainers:
            endpoints = trainers.split(",")
        else:
            endpoints = None
        if endpoints and len(endpoints) > 1:
            from .parallel import distributed as dist

            dist.init_distributed(trainer_id=trainer_id,
                                  trainer_endpoints=endpoints,
                                  current_endpoint=current_endpoint or None)
        return self

    def get_trainer_program(self, wait_port=True):
        from .parallel.compiled_program import CompiledProgram
        from .parallel.distributed import global_mesh

        if self._program is None:
            raise RuntimeError("call transpile() first")
        return CompiledProgram(self._program).with_mesh(global_mesh())

    def get_pserver_programs(self, endpoint):
        """reference DistributeTranspiler.get_pserver_programs: the
        (pserver_program, startup) pair."""
        main = self.get_pserver_program(endpoint)
        return main, getattr(self, "_startup", None)

    def get_pserver_program(self, endpoint):
        raise NotImplementedError(
            "get_pserver_program: no pserver role exists in the TPU build — "
            "every process is a trainer in one SPMD program (see transpile())")

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        from .core.program import default_startup_program

        return startup_program if startup_program is not None else default_startup_program()


class PSDispatcher:
    """reference transpiler/ps_dispatcher.py: assign parameter slices to
    pserver endpoints."""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    """reference ps_dispatcher.RoundRobin: cycle endpoints in order."""

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step])
            self._step = (self._step + 1) % len(self._eps)
        return out


class HashName(PSDispatcher):
    """reference ps_dispatcher.HashName: endpoint by name-hash bucket."""

    @staticmethod
    def _hash_block(block_str, total):
        import zlib

        return zlib.crc32(block_str.encode()) % total

    def dispatch(self, varlist):
        return [self._eps[self._hash_block(v.name if hasattr(v, "name") else str(v),
                                           len(self._eps))]
                for v in varlist]



def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """reference transpiler.memory_optimize (var reuse pass): accepted
    no-op — XLA buffer assignment + executor donation own memory reuse;
    BuildStrategy.memory_optimize drives rematerialization instead."""
    return None


def release_memory(input_program, skip_opt_set=None):
    """reference transpiler.release_memory: accepted no-op (XLA live-range
    analysis frees buffers)."""
    return None
