"""DistributeTranspiler source-compatibility layer.

Reference: transpiler/distribute_transpiler.py (DistributeTranspiler:183,
transpile:377) with three modes — pserver (default), nccl2:261,
collective:313 — rewriting programs into send/recv or c_allreduce graphs.

TPU-first mapping (SURVEY §2c):
  * collective / nccl2 modes -> NO program rewrite is needed: the executor
    emits ONE SPMD program whose gradient all-reduces GSPMD inserts.
    `transpile()` therefore only performs the bootstrap the `gen_nccl_id`
    op did (coordination service via parallel/distributed.py) and
    `get_trainer_program()` hands back the program compiled for the global
    mesh.
  * pserver mode for DENSE parameters is intentionally NOT implemented:
    allreduce strictly wins on ICI (SURVEY §2c) — `get_pserver_program`
    raises with that rationale.  The capability the pserver mode actually
    carried (sparse embedding tables) lives in the SelectedRows/ep path
    (core/selected_rows.py, parallel/embedding.py).
"""
from __future__ import annotations

from typing import Optional


class DistributeTranspilerConfig:
    """reference transpiler config: carriers kept for source compat."""

    def __init__(self):
        self.slice_var_up = True
        self.min_block_size = 8192
        self.mode = "collective"  # "nccl2" and "collective" behave the same
        self.sync_mode = True
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False  # ICI handles hierarchy


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._program = None

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None, current_endpoint=""):
        """collective/nccl2 semantics: bootstrap the process group and
        remember the program; no graph rewrite is required on TPU."""
        from .core.program import default_main_program

        # a non-empty `pservers` list IS the legacy pserver-mode request,
        # whatever the config says — fail with the rationale instead of
        # silently training unsynchronized replicas
        if pservers or self.config.mode == "pserver":
            raise NotImplementedError(
                "DistributeTranspiler: dense parameter-server mode is "
                "deliberately unimplemented on TPU — synchronous allreduce "
                "over ICI strictly dominates (SURVEY §2c); use the default "
                "collective mode. Sparse/giant tables: use is_sparse "
                "embeddings (SelectedRows) with ep-axis sharding instead.")
        self._program = program if program is not None else default_main_program()
        if isinstance(trainers, str) and trainers:
            endpoints = trainers.split(",")
        else:
            endpoints = None
        if endpoints and len(endpoints) > 1:
            from .parallel import distributed as dist

            dist.init_distributed(trainer_id=trainer_id,
                                  trainer_endpoints=endpoints,
                                  current_endpoint=current_endpoint or None)
        return self

    def get_trainer_program(self, wait_port=True):
        from .parallel.compiled_program import CompiledProgram
        from .parallel.distributed import global_mesh

        if self._program is None:
            raise RuntimeError("call transpile() first")
        return CompiledProgram(self._program).with_mesh(global_mesh())

    def get_pserver_program(self, endpoint):
        raise NotImplementedError(
            "get_pserver_program: no pserver role exists in the TPU build — "
            "every process is a trainer in one SPMD program (see transpile())")

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        from .core.program import default_startup_program

        return startup_program if startup_program is not None else default_startup_program()
