"""Serving-replica process entry: `python -m paddle_tpu.serving.replica_main`.

One fleet replica = one process running a full `serving.Server` (its own
registry, publisher ladder, monitor plane) behind a line-JSON TCP
control/data socket, plus a `ReplicaBeat` whose payload carries the
serving vitals the router dispatches on.  The fleet supervisor
(`serving/fleet.py`) spawns N of these; nothing in here knows about its
siblings — membership, routing and the rolling-publish protocol live
entirely supervisor-side.

Environment contract (set by `ServingFleet._spawn`):

    PADDLE_FLEET_DIR      fleet root: fleet.json (config), ACTIVE.json
                          (what to serve at boot), hb/ (beat files)
    PADDLE_TRAINER_ID     replica rank
    PADDLE_REPLICA_PORT   TCP port to serve on (127.0.0.1)
    PADDLE_TELEMETRY_DIR  per-incarnation monitor stream dir (the same
                          `metrics.p<rank>.jsonl` plane gang workers use;
                          `serve_trace --fleet` merges them)
    FLAGS_fault_spec      optional: arms storage-fault injection in THIS
                          replica (chaos tests rot/eio the shared store
                          from inside the replica running the ladder)

Wire protocol (newline-delimited JSON, one request per connection —
see serving/router.py): ops `infer`, `stats`, `ping`, and the
supervisor-only roll plane `stage` / `activate` / `discard` /
`rollback` / `active_src`.  Every reply is `{"ok": true, ...}` or
`{"ok": false, "reason": <classified>, "error": <message>}`.

Shutdown: SIGTERM starts a drain (the handler is installed at the top
of main(), so a SIGTERM landing mid-model-load still drains and exits
0; one landing even earlier — during interpreter/package import — kills
the process with -SIGTERM, which the supervisor ALSO treats as
deliberate retirement, never a restartable death) — the beat payload
flips
`draining=true` immediately (one `beat_now`, so the router stops
dispatching within one health poll), dispatched-but-unfinished requests
are served out, the final ledger snapshot is written, and the process
exits 0 (the supervisor's "deliberate drain, do not restart" code).
SIGKILL is the chaos case: the periodic in-loop snapshots are what
survives for `serve_trace --fleet` reconciliation.
"""
from __future__ import annotations

import json
import os
import signal
import socketserver
import sys
import threading
import time

REPLICA_EXIT_CONFIG = 41  # bad/missing env or fleet.json: not restartable


def _reply(wfile, doc: dict):
    wfile.write((json.dumps(doc, default=str) + "\n").encode("utf-8"))
    wfile.flush()


def _classified(exc) -> dict:
    reason = getattr(exc, "reason", None) or "error"
    return {"ok": False, "reason": reason, "error": str(exc),
            "trace_id": getattr(exc, "trace_id", None)}


def _make_handler(ctx):
    """Request handler bound to this replica's server/registry.  `ctx`
    carries srv, registry, buckets, draining flag holder."""
    from . import publisher as _pub
    from .router import decode_feeds, encode_arrays

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            line = self.rfile.readline()
            if not line:
                return
            try:
                msg = json.loads(line.decode("utf-8"))
            except ValueError as e:
                _reply(self.wfile, {"ok": False, "reason": "bad_request",
                                    "error": f"undecodable request: {e}"})
                return
            op = msg.get("op")
            try:
                _reply(self.wfile, self._dispatch(op, msg))
            except Exception as e:  # classified or not, the wire answers
                try:
                    _reply(self.wfile, _classified(e))
                except OSError:
                    pass  # client hung up first

        def _dispatch(self, op, msg):
            srv = ctx["srv"]
            registry = srv.registry
            if op == "ping":
                return {"ok": True, "pid": os.getpid(),
                        "rank": ctx["rank"]}
            if op == "infer":
                out = srv.infer(msg["model"], decode_feeds(msg["feeds"]),
                                deadline_ms=msg.get("deadline_ms"))
                return {"ok": True, "outputs": encode_arrays(out)}
            if op == "stats":
                return {"ok": True, "stats": srv.stats(),
                        "draining": ctx["draining"].is_set(),
                        "pid": os.getpid()}
            if op == "stage":
                version = _pub.publish(
                    registry, msg["model"], msg["src"], stage_only=True,
                    warm_buckets=ctx["buckets"])
                return {"ok": True, "version": version.version,
                        "src": version.src}
            if op == "activate":
                registry.activate_staged(msg["model"])
                return {"ok": True,
                        "version": registry.models()[msg["model"]]["version"]}
            if op == "discard":
                return {"ok": True,
                        "discarded": registry.discard_staged(msg["model"])}
            if op == "rollback":
                registry.rollback(msg["model"])
                return {"ok": True}
            if op == "active_src":
                info = registry.models().get(msg["model"])
                if info is None:
                    return {"ok": False, "reason": "model_missing",
                            "error": f"no model {msg['model']!r} loaded"}
                return {"ok": True, "src": info.get("src"),
                        "version": info.get("version")}
            return {"ok": False, "reason": "bad_request",
                    "error": f"unknown op {op!r}"}

    return Handler


class _Listener(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def main() -> int:
    fleet_dir = os.environ.get("PADDLE_FLEET_DIR")
    port = os.environ.get("PADDLE_REPLICA_PORT")
    if not fleet_dir or not port:
        print("replica_main: PADDLE_FLEET_DIR and PADDLE_REPLICA_PORT "
              "are required", file=sys.stderr)
        return REPLICA_EXIT_CONFIG
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    port = int(port)

    # the drain handler goes in BEFORE the slow part of boot (model
    # load, bucket warm): a SIGTERM racing a booting replica must still
    # be a deliberate drain (exit 0), not the default handler's
    # non-zero death that the supervisor would dutifully restart —
    # undoing an operator scale-down or fleet.stop()
    draining = threading.Event()
    done = threading.Event()

    def _sigterm(_sig, _frm):
        draining.set()
        done.set()

    signal.signal(signal.SIGTERM, _sigterm)

    from .. import io as _io
    from .. import monitor
    from ..dist_resilience import ReplicaBeat
    from ..faults import FaultInjector
    from ..monitor.exporters import init_worker_telemetry
    from .registry import ModelRegistry
    from .server import Server

    try:
        cfg = _io.read_json(os.path.join(fleet_dir, "fleet.json"))
    except OSError as e:
        print(f"replica_main: unreadable fleet.json: {e}", file=sys.stderr)
        return REPLICA_EXIT_CONFIG

    monitor.enable()
    logger = init_worker_telemetry(rank=rank)

    injector = FaultInjector.from_flags()
    if injector is not None:
        injector.arm_io()

    buckets = tuple(cfg.get("buckets") or (1, 4, 8))
    hb_interval = float(cfg.get("hb_interval_s", 0.5))
    drain_grace = float(cfg.get("drain_grace_s", 4 * hb_interval))
    world = int(cfg.get("n_replicas", 1))

    registry = ModelRegistry()
    srv = Server(registry, buckets=buckets,
                 max_queue=cfg.get("max_queue"),
                 default_deadline_ms=cfg.get("default_deadline_ms"),
                 workers=int(cfg.get("workers", 1)))

    # boot on the fleet-active versions (ACTIVE.json is only ever moved
    # forward AFTER every replica acked a roll, so a restart mid-roll
    # lands on the last good version and the supervisor re-stages)
    active = {}
    try:
        active = _io.read_json(os.path.join(fleet_dir, "ACTIVE.json"))
    except OSError:
        pass  # first boot before any roll: fleet.json names the models
    models = (active.get("models") if isinstance(active, dict) else None) \
        or cfg.get("models") or {}
    for name, spec in models.items():
        src = spec["src"] if isinstance(spec, dict) else spec
        srv.load_model(name, src)

    ctx = {"srv": srv, "rank": rank, "buckets": buckets,
           "draining": draining}

    listener = _Listener(("127.0.0.1", port), _make_handler(ctx))
    listen_thread = threading.Thread(target=listener.serve_forever,
                                     name="replica-listener", daemon=True)
    listen_thread.start()

    # beat payload: the vitals the router routes on.  Every Nth beat also
    # appends a monitor snapshot so a SIGKILLed replica still leaves an
    # (at most one beat stale) ledger for fleet reconciliation.
    snap_every = max(int(cfg.get("snapshot_every_beats", 2)), 1)
    beat_n = [0]

    def _payload():
        beat_n[0] += 1
        if logger is not None and beat_n[0] % snap_every == 0:
            try:
                logger.write_snapshot()
            except OSError:
                pass
        s = srv.stats()
        return {"port": port, "pid": os.getpid(),
                "q": s["queue_depth"],
                "p99": s.get("lat_p99_ms", 0.0),
                "shed": s["shed"] + s["rejected"],
                "completed": s["completed"],
                "draining": draining.is_set(),
                "active": {n: m["version"]
                           for n, m in s["models"].items()}}

    beat = ReplicaBeat(os.path.join(fleet_dir, "hb"), rank, world,
                       interval_s=hb_interval, payload_fn=_payload).start()

    monitor.record_step({"kind": "serving_event", "action": "replica_up",
                         "rank": rank, "port": port, "pid": os.getpid()})
    done.wait()

    # -- drain --------------------------------------------------------------
    beat.beat_now()          # draining=true reaches the router NOW
    time.sleep(drain_grace)  # let already-dispatched connections land
    listener.shutdown()      # stop accepting; in-flight handlers finish
    srv.stop(drain=True)     # serve out everything admitted
    listener.server_close()
    monitor.record_step({"kind": "serving_event", "action": "replica_drained",
                         "rank": rank, "pid": os.getpid()})
    if logger is not None:
        try:
            logger.write_snapshot()
        except OSError:
            pass
    beat.stop(mark_down=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
