"""Serving fleet supervisor: N replica processes, health-routed, rolled.

`ServingFleet` generalizes the gang supervision machinery in
`paddle_tpu.launch` (heartbeat liveness, watchdogged restart with a
budget, per-incarnation telemetry dirs) for SERVING processes — where a
gang restarts as a unit because training steps are collective, a fleet
restarts replicas INDEPENDENTLY because requests are not:

  * each replica is one `serving.replica_main` process (full Server +
    publisher ladder + monitor plane) beating `ReplicaBeat` files under
    `<root>/hb/`;
  * the supervisor watches `FleetHealth` + process exit codes: exit 0
    (a completed drain) or death BY SIGTERM (the drain signal caught a
    replica mid-boot, before its handler existed) is deliberate
    retirement — never restarted; anything else is a death — restarted
    with a fresh telemetry incarnation until the per-replica restart
    budget is spent;
  * traffic rides `serving.router.Router` over the same health table:
    a dead replica loses only its own in-flight requests (classified
    `reason="replica_down"`), new traffic redistributes within one
    heartbeat miss window (sooner when a connect fails — see router
    suspicion);
  * `rolling_publish` is the zero-downtime reload: phase one stages the
    new snapshot through every replica ONE AT A TIME — each runs the
    full verification ladder (torn-commit, digest, NaN, golden smoke,
    quant parity, bucket warm) via `publish(stage_only=True)` while its
    old version keeps serving; phase two activates replica by replica.
    A rung failure anywhere HALTS the roll and converges the fleet back
    on the last good version (staged slots discarded everywhere, zero
    requests ever served by the bad version).  No split-brain: the
    fleet-active pointer (`ACTIVE.json`, what a restarted replica boots
    from) moves only after EVERY replica acked the activate AND a final
    reconcile pass re-verified each ack against the replica's live
    active version (an acked replica that died and rebooted on last
    good is re-staged + re-activated, not trusted).  The roll
    itself is crash-safe: progress persists in `ROLL.json` (io.py
    atomic write) and a replica death mid-roll is waited out — the
    restarted replica boots on last good and is re-staged.

Fleet telemetry: the supervisor appends monitor-shaped records to
`<root>/telemetry/router.jsonl` — `fleet_event` records (replica_dead /
replica_restarted / roll_started / replica_staged / roll_halted /
roll_converged / roll_rolled_back / ...) plus periodic snapshots whose
gauges carry `serving.fleet.healthy_replicas` / `.size` /
`.roll_active` and whose counters mirror the router ledger.
`tools/serve_trace.py --fleet` merges this with the per-replica
`metrics.p<rank>.jsonl` streams; `tools/perf_report.py --check` gates
on the gauges and on roll convergence.
"""
from __future__ import annotations

__all__ = ["ServingFleet"]

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from .. import io as _io
from ..core import locks
from ..errors import ServingError
from ..launch import REPO_ROOT, allocate_port_block, worker_env
from ..monitor import MONITOR as _MON
from ..dist_resilience import FleetHealth
from .router import ConnectFailed, Router, rpc
from .tracing import control_trace_id

_ROLL_FILE = "ROLL.json"
_ACTIVE_FILE = "ACTIVE.json"


class ServingFleet:
    """Supervised fleet of replica servers behind a health-aware router.

        fleet = ServingFleet({"m": "/models/m"}, n_replicas=2,
                             root="/tmp/fleet")
        fleet.wait_healthy()
        out = fleet.infer("m", {"x": batch})
        fleet.rolling_publish("m", "/models/m_v2")   # zero-downtime
        fleet.stop()
    """

    def __init__(self, models: Dict[str, str], n_replicas: int = 2,
                 root: Optional[str] = None, buckets=(1, 4, 8),
                 hb_interval_s: float = 0.3, miss_factor: float = 4.0,
                 startup_grace_s: float = 60.0, inflight_cap: int = 8,
                 max_restarts: int = 3, drain_grace_s: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 rpc_timeout_s: float = 60.0,
                 extra_env: Optional[Dict[str, str]] = None,
                 per_rank_env: Optional[Dict[int, Dict[str, str]]] = None,
                 start: bool = True):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.models = dict(models)
        self.n = int(n_replicas)
        self.root = root or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"paddle_fleet_{os.getpid()}")
        self.hb_interval_s = float(hb_interval_s)
        self.max_restarts = int(max_restarts)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.extra_env = dict(extra_env or {})
        self.per_rank_env = {int(r): dict(e)
                             for r, e in (per_rank_env or {}).items()}
        os.makedirs(os.path.join(self.root, "hb"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "telemetry"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "logs"), exist_ok=True)
        self._router_log = os.path.join(self.root, "telemetry",
                                        "router.jsonl")
        self.config = {
            "n_replicas": self.n, "buckets": list(buckets),
            "hb_interval_s": self.hb_interval_s,
            "models": {n: {"src": src} for n, src in self.models.items()},
            "max_queue": max_queue,
            "default_deadline_ms": default_deadline_ms,
            "drain_grace_s": (drain_grace_s if drain_grace_s is not None
                              else 4 * self.hb_interval_s),
        }
        _io.atomic_write(os.path.join(self.root, "fleet.json"),
                         json.dumps(self.config, indent=1))
        self.health = FleetHealth(os.path.join(self.root, "hb"), self.n,
                                  interval_s=self.hb_interval_s,
                                  miss_factor=miss_factor,
                                  startup_grace_s=startup_grace_s)
        self.router = Router(self.health, inflight_cap=inflight_cap,
                             rpc_timeout_s=rpc_timeout_s)
        base = allocate_port_block(self.n)
        self._ports = [base + i for i in range(self.n)]
        # replica table; every blocking op (spawn, wait, rpc) runs OUTSIDE
        # this lock — it guards only the table itself
        self._lock = locks.named_lock("serving.fleet", rank=4)
        self._replicas: Dict[int, dict] = {}
        self._incarnation = 0
        self._stopping = False
        self._roll_active = False
        self._sup_thread: Optional[threading.Thread] = None
        self._sup_stop = threading.Event()
        if start:
            self.start()

    # -- telemetry ----------------------------------------------------------
    def _event(self, action: str, **fields):
        from ..monitor import record_fleet_event

        self._append_log(record_fleet_event(action, **fields))

    def _append_log(self, rec: dict):
        line = json.dumps(rec, default=str) + "\n"
        try:
            with _io.fault_exempt(self.root):
                with open(self._router_log, "a") as f:
                    f.write(line)
                    f.flush()
        except OSError:
            _MON.counter("serving.fleet.log_errors").inc()

    def _snapshot(self):
        """One monitor-shaped snapshot line: router ledger as counters,
        fleet liveness as gauges (what `perf_report --check` gates on)."""
        table = self.health.poll()
        healthy = sum(1 for i in table.values() if i["status"] == "alive")
        with self._lock:
            roll = self._roll_active
        led = self.router.stats()
        counters = {"serving.fleet.requests": led["requests"],
                    "serving.fleet.completed": led["completed"],
                    "serving.fleet.errors": led["errors"],
                    "serving.fleet.retries": led["retries"]}
        for reason, n in led["by_reason"].items():
            counters[f"serving.fleet.errors[{reason}]"] = n
        for rank, n in led["routed"].items():
            counters[f"serving.fleet.routed[{rank}]"] = n
        gauges = {"serving.fleet.healthy_replicas": float(healthy),
                  "serving.fleet.size": float(self.n),
                  "serving.fleet.roll_active": 1.0 if roll else 0.0}
        _MON.gauge("serving.fleet.healthy_replicas").set(float(healthy))
        _MON.gauge("serving.fleet.size").set(float(self.n))
        _MON.gauge("serving.fleet.roll_active").set(1.0 if roll else 0.0)
        self._append_log({"kind": "snapshot", "ts": time.time(),
                          "lane": -1, "lane_name": "router",
                          "counters": counters, "gauges": gauges,
                          "replicas": {r: i["status"]
                                       for r, i in table.items()}})

    # -- lifecycle ----------------------------------------------------------
    def _spawn(self, rank: int, restarts: int) -> dict:
        with self._lock:
            self._incarnation += 1
            inc = self._incarnation
        tel_dir = os.path.join(self.root, "telemetry", f"i{inc}")
        os.makedirs(tel_dir, exist_ok=True)
        endpoints = [f"127.0.0.1:{p}" for p in self._ports]
        env = worker_env(rank, endpoints, 1, extra={
            "PADDLE_FLEET_DIR": self.root,
            "PADDLE_REPLICA_PORT": str(self._ports[rank]),
            "PADDLE_TELEMETRY_DIR": tel_dir,
            "PADDLE_RESTART_NUM": str(restarts),
        })
        env.update(self.extra_env)
        env.update(self.per_rank_env.get(rank, {}))
        out = open(os.path.join(self.root, "logs",
                                f"replica{rank}.i{inc}.out"), "wb")
        err = open(os.path.join(self.root, "logs",
                                f"replica{rank}.i{inc}.err"), "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.replica_main"],
            env=env, cwd=REPO_ROOT, stdout=out, stderr=err)
        return {"proc": proc, "port": self._ports[rank],
                "restarts": restarts, "retired": False,
                "spool": (out, err), "incarnation": inc}

    def start(self) -> "ServingFleet":
        with self._lock:
            if self._replicas:
                return self
        for rank in range(self.n):
            rep = self._spawn(rank, 0)
            with self._lock:
                self._replicas[rank] = rep
        self._event("fleet_started", n_replicas=self.n,
                    ports=self._ports,
                    models={n: s for n, s in self.models.items()})
        self._sup_thread = threading.Thread(
            target=self._supervise, name="fleet-supervisor", daemon=True)
        self._sup_thread.start()
        return self

    def _supervise(self):
        """Watch exits + health; restart non-retired deaths within budget.
        Also the fleet's snapshot heartbeat."""
        while not self._sup_stop.wait(self.hb_interval_s):
            with self._lock:
                if self._stopping:
                    return
                table = dict(self._replicas)
            for rank, rep in table.items():
                rc = rep["proc"].poll()
                if rc is None or rep["retired"]:
                    continue
                self._close_spool(rep)
                if rc == 0 or rc == -signal.SIGTERM:
                    # deliberate drain: exit 0 is the replica announcing
                    # its own retirement; -SIGTERM means the drain signal
                    # landed before the replica's handler was even
                    # installed (interpreter/package import is the slow
                    # part of boot) — the INTENT was still retirement, and
                    # restarting would undo an operator's scale-down
                    with self._lock:
                        rep["retired"] = True
                    self._event("replica_retired", rank=rank, exit_code=rc)
                    continue
                _MON.counter("serving.fleet.replica_deaths").inc()
                self._event("replica_dead", rank=rank, exit_code=rc,
                            restarts=rep["restarts"])
                if rep["restarts"] >= self.max_restarts:
                    with self._lock:
                        rep["retired"] = True
                    self._event("replica_abandoned", rank=rank,
                                restarts=rep["restarts"])
                    continue
                self.health.note_restart(rank)
                # router suspicion was pinned to the DEAD incarnation's
                # beat seq; the fresh process counts from 1 and would
                # otherwise stay benched until it outran the corpse
                self.router.note_restart(rank)
                fresh = self._spawn(rank, rep["restarts"] + 1)
                with self._lock:
                    self._replicas[rank] = fresh
                self._event("replica_restarted", rank=rank,
                            restarts=fresh["restarts"],
                            incarnation=fresh["incarnation"])
            self._snapshot()

    @staticmethod
    def _close_spool(rep: dict):
        for f in rep.get("spool") or ():
            try:
                f.close()
            except OSError:
                pass

    def wait_healthy(self, min_replicas: Optional[int] = None,
                     timeout: float = 120.0) -> List[int]:
        """Block until `min_replicas` (default: all) replicas are alive
        AND listening (their beat payload carries the serving port)."""
        need = self.n if min_replicas is None else int(min_replicas)
        deadline = time.monotonic() + timeout
        while True:
            table = self.health.poll()
            up = [r for r, i in table.items()
                  if i["status"] == "alive"
                  and (i.get("tel") or {}).get("port")]
            if len(up) >= need:
                return sorted(up)
            if time.monotonic() > deadline:
                raise ServingError(
                    f"fleet failed to reach {need} healthy replicas "
                    f"within {timeout:.0f}s (have {sorted(up)}; "
                    f"statuses {[i['status'] for i in table.values()]})",
                    reason="replica_down")
            time.sleep(self.hb_interval_s / 2)

    def stop(self, timeout: float = 30.0):
        """Drain and stop every replica (SIGTERM -> grace -> SIGKILL),
        stop supervision, write the final ledger snapshot."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
        self._sup_stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout=10.0)
            self._sup_thread = None
        # final gauge snapshot BEFORE the drain: `healthy_replicas` must
        # record the fleet as it served, not the deliberate teardown
        # (perf_report --min-healthy-replicas gates this snapshot)
        self._snapshot()
        with self._lock:
            table = dict(self._replicas)
        for rep in table.values():
            if rep["proc"].poll() is None:
                try:
                    rep["proc"].send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + timeout
        for rank, rep in table.items():
            left = max(deadline - time.monotonic(), 0.1)
            try:
                rep["proc"].wait(timeout=left)
            except subprocess.TimeoutExpired:
                rep["proc"].kill()
                rep["proc"].wait(timeout=10.0)
            self._close_spool(rep)
        self._event("fleet_stopped",
                    ledger=self.router.stats())

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- request plane ------------------------------------------------------
    def infer(self, model: str, feeds, deadline_ms=None):
        return self.router.infer(model, feeds, deadline_ms=deadline_ms)

    def stats(self) -> dict:
        return self.router.stats()

    def replica_stats(self, rank: int) -> dict:
        """One replica's own ledger (op=stats over the control plane)."""
        with self._lock:
            port = self._replicas[rank]["port"]
        return rpc(port, {"op": "stats"}, timeout_s=self.rpc_timeout_s)

    def active_versions(self, model: str) -> Dict[int, dict]:
        """Each LIVE replica's active {src, version} for `model` — the
        split-brain probe chaos tests assert on."""
        out = {}
        table = self.health.poll()
        for rank, info in table.items():
            if info["status"] not in ("alive", "draining"):
                continue
            with self._lock:
                port = self._replicas[rank]["port"]
            try:
                reply = rpc(port, {"op": "active_src", "model": model},
                            timeout_s=self.rpc_timeout_s)
            except OSError:
                continue
            if reply.get("ok"):
                out[rank] = {"src": reply.get("src"),
                             "version": reply.get("version")}
        return out

    # -- rolling publish ----------------------------------------------------
    def _persist_roll(self, roll: dict):
        _io.atomic_write(os.path.join(self.root, _ROLL_FILE),
                         json.dumps(roll, indent=1))

    def _load_roll(self) -> Optional[dict]:
        try:
            doc = _io.read_json(os.path.join(self.root, _ROLL_FILE))
            return doc if isinstance(doc, dict) else None
        except OSError:
            return None

    def _control_rpc(self, rank: int, msg: dict,
                     recover_timeout: float = 60.0) -> dict:
        """Roll-plane rpc with crash recovery: a replica that dies while
        verifying is waited out (the supervisor restarts it; the fresh
        incarnation boots on last good) and the op is retried there."""
        deadline = time.monotonic() + recover_timeout
        while True:
            with self._lock:
                rep = self._replicas[rank]
                port, retired = rep["port"], rep["retired"]
            if retired:
                raise ServingError(
                    f"replica rank {rank} is retired (restart budget "
                    f"spent or drained); the fleet cannot complete this "
                    f"roll step", reason="replica_down")
            try:
                return rpc(port, msg, timeout_s=self.rpc_timeout_s)
            except (ConnectFailed, OSError) as e:
                if time.monotonic() > deadline:
                    raise ServingError(
                        f"replica rank {rank} unreachable for "
                        f"{recover_timeout:.0f}s during a roll step: {e}",
                        reason="replica_down") from e
                time.sleep(self.hb_interval_s)

    def rolling_publish(self, name: str, src: str,
                        recover_timeout: float = 60.0):
        """Zero-downtime verified publish through every replica.

        Phase "verify": each replica (one at a time) runs the FULL
        publish ladder on `src` with `stage_only=True` — old version
        keeps serving throughout.  Phase "activate": each replica swaps
        its staged version in; `ACTIVE.json` (what replica restarts
        boot from) moves only after every replica acked.  Any rung
        failure halts the roll and converges the fleet back on the last
        good version; raises `ServingError(reason="roll_halted")` with
        the original failure chained."""
        roll = {"model": name, "src": src,
                "ctl": control_trace_id("roll"),
                "phase": "verify", "verified": [], "acked": [],
                "last_good": (self.config["models"].get(name) or {}
                              ).get("src"), "ts": time.time()}
        return self._run_roll(roll, recover_timeout)

    def resume_roll(self, recover_timeout: float = 60.0):
        """Finish (or converge) a roll interrupted by a supervisor crash,
        from the persisted `ROLL.json` state.  Returns None when there is
        nothing to resume."""
        roll = self._load_roll()
        if not roll or roll.get("phase") in ("done", "rolled_back", None):
            return None
        if roll["phase"] == "halted":
            try:
                self._converge_back(roll, ServingError(
                    "resuming a roll persisted as halted",
                    reason="roll_halted"))
            except ServingError:
                pass  # convergence done; the original roll already failed
            return self._load_roll()
        self._event("roll_resumed", ctl=roll.get("ctl"),
                    phase=roll.get("phase"), model=roll.get("model"))
        return self._run_roll(roll, recover_timeout, resumed=True)

    def _run_roll(self, roll: dict, recover_timeout: float,
                  resumed: bool = False):
        name, src, ctl = roll["model"], roll["src"], roll["ctl"]
        with self._lock:
            if self._roll_active:
                raise ServingError(
                    "another rolling publish is already in flight",
                    reason="publish_rejected", model=name)
            self._roll_active = True
        try:
            if not resumed:
                self._persist_roll(roll)
                self._event("roll_started", ctl=ctl, model=name, src=src,
                            last_good=roll["last_good"])
            if roll["phase"] == "verify":
                for rank in range(self.n):
                    if rank in roll["verified"]:
                        continue
                    try:
                        reply = self._control_rpc(
                            rank, {"op": "stage", "model": name,
                                   "src": src},
                            recover_timeout=recover_timeout)
                    except ServingError as e:
                        self._halt_roll(roll, rank, e)
                    if not reply.get("ok"):
                        self._halt_roll(roll, rank, ServingError(
                            reply.get("error") or "stage refused",
                            reason=reply.get("reason") or
                            "publish_rejected", model=name,
                            trace_id=reply.get("trace_id")))
                    roll["verified"].append(rank)
                    self._persist_roll(roll)
                    self._event("replica_staged", ctl=ctl, model=name,
                                rank=rank, version=reply.get("version"))
                roll["phase"] = "activate"
                self._persist_roll(roll)
            for rank in range(self.n):
                if rank in roll["acked"]:
                    continue
                reply = self._activate_one(roll, rank, recover_timeout)
                roll["acked"].append(rank)
                self._persist_roll(roll)
                self._event("replica_acked", ctl=ctl, model=name,
                            rank=rank, version=reply.get("version"))
            # every replica acked — but an ack is not proof the replica
            # is still serving the new version: one that died AFTER
            # acking was restarted from ACTIVE.json (still last good)
            # and the loop above skips acked ranks.  Re-verify before
            # the pointer moves, or that replica split-brains forever.
            self._reconcile_acked(roll, recover_timeout)
            # the version becomes FLEET-active — this pointer is what
            # replica restarts boot from
            self.config["models"][name] = {"src": src}
            _io.atomic_write(
                os.path.join(self.root, _ACTIVE_FILE),
                json.dumps({"models": self.config["models"],
                            "ctl": ctl, "ts": time.time()}, indent=1))
            roll["phase"] = "done"
            self._persist_roll(roll)
            self._event("roll_converged", ctl=ctl, model=name, src=src,
                        acked=roll["acked"])
            return roll
        finally:
            with self._lock:
                self._roll_active = False

    def _activate_one(self, roll: dict, rank: int,
                      recover_timeout: float) -> dict:
        """Activate on one replica; a replica that died between stage and
        activate lost its (in-memory) staged slot — re-stage it first."""
        name, src = roll["model"], roll["src"]
        for attempt in range(2):
            try:
                reply = self._control_rpc(
                    rank, {"op": "activate", "model": name},
                    recover_timeout=recover_timeout)
            except ServingError as e:
                self._halt_roll(roll, rank, e)
            if reply.get("ok"):
                return reply
            if reply.get("reason") == "model_missing" and attempt == 0:
                # restarted mid-roll: boots on last good, staged slot
                # empty — run the ladder again on the fresh incarnation
                restage = self._control_rpc(
                    rank, {"op": "stage", "model": name, "src": src},
                    recover_timeout=recover_timeout)
                if not restage.get("ok"):
                    self._halt_roll(roll, rank, ServingError(
                        restage.get("error") or "re-stage refused",
                        reason=restage.get("reason") or "publish_rejected",
                        model=name))
                self._event("replica_restaged", ctl=roll["ctl"],
                            model=name, rank=rank)
                continue
            self._halt_roll(roll, rank, ServingError(
                reply.get("error") or "activate refused",
                reason=reply.get("reason") or "publish_rejected",
                model=name))
        raise AssertionError("unreachable")  # _halt_roll always raises

    def _reconcile_acked(self, roll: dict, recover_timeout: float):
        """Close the ack-then-die window before the roll finalizes: ask
        every acked replica what it is ACTUALLY serving (op=active_src)
        and re-run stage+activate on any that silently reverted — a
        replica restarted after its ack boots from ACTIVE.json, which is
        still the last good version until this pass comes back clean.
        Repeats until one full pass verifies, so a death during the
        reconcile itself is caught by the next pass."""
        name, src, ctl = roll["model"], roll["src"], roll["ctl"]
        for _ in range(self.max_restarts + 2):
            reverted = []
            for rank in list(roll["acked"]):
                try:
                    reply = self._control_rpc(
                        rank, {"op": "active_src", "model": name},
                        recover_timeout=recover_timeout)
                except ServingError as e:
                    self._halt_roll(roll, rank, e)
                if not (reply.get("ok") and reply.get("src") == src):
                    reverted.append(rank)
            if not reverted:
                return
            for rank in reverted:
                # boots on last good with an empty staged slot, so
                # _activate_one's model_missing path re-runs the ladder
                reply = self._activate_one(roll, rank, recover_timeout)
                self._event("replica_reactivated", ctl=ctl, model=name,
                            rank=rank, version=reply.get("version"))
        self._halt_roll(roll, reverted[0], ServingError(
            f"replica rank {reverted[0]} kept reverting to the last "
            f"good version while finalizing the roll (restart loop?)",
            reason="publish_rejected", model=name))

    def _halt_roll(self, roll: dict, rank: int, cause: ServingError):
        """A rung failed: halt, converge the fleet back on last good,
        raise classified.  Never returns."""
        roll["phase"] = "halted"
        roll["failed_rank"] = rank
        roll["failure"] = {"reason": cause.reason, "error": str(cause)}
        self._persist_roll(roll)
        _MON.counter("serving.fleet.rolls_halted").inc()
        self._event("roll_halted", ctl=roll["ctl"], model=roll["model"],
                    rank=rank, reason=cause.reason, error=str(cause))
        self._converge_back(roll, cause)

    def _converge_back(self, roll: dict, cause: ServingError):
        """Discard every staged slot (and roll back any replica that
        already activated) so the whole fleet serves last good again."""
        name = roll["model"]
        for rank in roll.get("acked", []):
            try:
                self._control_rpc(rank, {"op": "rollback", "model": name},
                                  recover_timeout=10.0)
            except ServingError:
                pass  # a dead acked replica reboots on last good anyway
        for rank in roll.get("verified", []):
            if rank in roll.get("acked", []):
                continue
            try:
                self._control_rpc(rank, {"op": "discard", "model": name},
                                  recover_timeout=10.0)
            except ServingError:
                pass
        roll["phase"] = "rolled_back"
        self._persist_roll(roll)
        self._event("roll_rolled_back", ctl=roll["ctl"], model=name,
                    last_good=roll.get("last_good"))
        raise ServingError(
            f"rolling publish of {roll.get('src')!r} halted at replica "
            f"rank {roll.get('failed_rank')} and the fleet converged "
            f"back on the last good version: {cause}",
            reason="roll_halted", model=name) from cause
