"""Bucket policy + continuous-batch assembly for the serving runtime.

The recompile problem is the TPU-specific half of serving: every novel
feed shape is a fresh XLA compile (seconds), and a public endpoint sees
every batch size.  The policy here is the standard pad-to-bucket answer:
the server compiles a FIXED ladder of batch buckets (FLAGS_serving_buckets,
default 1,2,4,8,16,32) per model, warms them at load (or in the
publisher's pre-swap compile lane), and every request batch pads up to
the next bucket — so steady-state serving NEVER compiles inline, which
`perf_report --check`'s recompile-flat gate pins on the serving metrics
stream.

Padding repeats the batch's first row instead of writing zeros: padding
is dead compute either way (rows past `rows` are sliced off before any
client sees them), but zero rows can push models through poles the real
data never visits (log(0), division by a zero norm) and a NaN produced
in a PAD row would still trip FLAGS_check_nan_inf for the whole batch.
Repeating a real row keeps pad numerics inside the data distribution.

Everything here is pure (no queue, no threads): `Server` owns the queue
and calls in.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..errors import ServingError
from ..flags import flag as _flag

__all__ = ["DEFAULT_BUCKETS", "parse_buckets", "bucket_for", "batch_rows",
           "validate_feeds", "pad_feeds", "concat_feeds", "split_rows",
           "coalesce", "build_batch"]

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


def parse_buckets(spec=None) -> Tuple[int, ...]:
    """Sorted, deduplicated bucket ladder from a sequence or a
    comma-separated string (None -> FLAGS_serving_buckets)."""
    if spec is None:
        spec = _flag("FLAGS_serving_buckets") or ""
    if isinstance(spec, str):
        parts = [p.strip() for p in spec.split(",") if p.strip()]
        sizes = [int(p) for p in parts]
    else:
        sizes = [int(s) for s in spec]
    sizes = sorted(set(sizes))
    if not sizes or sizes[0] < 1:
        raise ValueError(f"serving buckets must be positive ints, got {spec!r}")
    return tuple(sizes)


def bucket_for(rows: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits `rows`; classified refusal past the top
    (an oversize request must be split by the CLIENT — silently chunking
    it would reorder its rows relative to admission)."""
    for b in buckets:
        if rows <= b:
            return b
    raise ServingError(
        f"request carries {rows} rows but the largest compiled bucket is "
        f"{buckets[-1]}; split the request or widen FLAGS_serving_buckets",
        reason="oversize")


def batch_rows(feeds: Dict[str, np.ndarray]) -> int:
    """The (validated) leading batch dim shared by every feed."""
    rows = None
    for name, v in feeds.items():
        shape = np.shape(v)
        if len(shape) == 0:
            raise ServingError(
                f"feed {name!r} is a scalar; serving feeds carry a leading "
                f"batch dim", reason="bad_request")
        if rows is None:
            rows = int(shape[0])
        elif int(shape[0]) != rows:
            raise ServingError(
                f"feed {name!r} has batch dim {shape[0]} but the request's "
                f"other feeds have {rows}", reason="bad_request")
    if not rows:
        raise ServingError("empty request (0 rows)", reason="bad_request")
    return rows


def validate_feeds(feeds: Dict[str, np.ndarray], feed_names: Sequence[str],
                   block) -> None:
    """Admission-time request validation against the model's feed
    contract: exact feed-name set (an EXTRA feed would also change the
    compile-cache signature and defeat the bucket warm) and declared
    trailing dims (the batch dim is free).  A malformed request must
    fail ALONE at the door — coalesced into a batch, its shape error
    would fail every innocent request batched with it."""
    missing = sorted(set(feed_names) - set(feeds))
    extra = sorted(set(feeds) - set(feed_names))
    if missing or extra:
        raise ServingError(
            f"request feeds do not match the model's contract "
            f"(missing {missing}, unexpected {extra})",
            reason="bad_request")
    for n in feed_names:
        shape = tuple(np.shape(feeds[n]))
        declared = list(block.var(n).shape or []) if block.has_var(n) else []
        if not declared:
            continue
        if (len(shape) != len(declared)
                or any(d >= 0 and s != d
                       for s, d in zip(shape[1:], declared[1:]))):
            raise ServingError(
                f"feed {n!r} shape {shape} does not match the declared "
                f"{declared} (batch dim free)", reason="bad_request")


def concat_feeds(feed_list: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Stack several requests' feeds along the batch dim (axis 0)."""
    names = feed_list[0].keys()
    return {n: np.concatenate([np.asarray(f[n]) for f in feed_list], axis=0)
            for n in names}


def pad_feeds(feeds: Dict[str, np.ndarray], bucket: int) -> Dict[str, np.ndarray]:
    """Pad every feed's batch dim up to `bucket` by repeating row 0."""
    out = {}
    for n, v in feeds.items():
        arr = np.asarray(v)
        pad = bucket - arr.shape[0]
        if pad < 0:
            raise ServingError(
                f"feed {n!r}: {arr.shape[0]} rows exceed bucket {bucket}",
                reason="oversize")
        if pad:
            filler = np.repeat(arr[:1], pad, axis=0)
            arr = np.concatenate([arr, filler], axis=0)
        out[n] = arr
    return out


def split_rows(outputs: Sequence[np.ndarray], offsets: Sequence[Tuple[int, int]],
               padded_rows: int) -> List[List[np.ndarray]]:
    """Slice a padded batch's outputs back into per-request results.

    `offsets` is [(start, stop), ...] per request in concat order.  An
    output whose leading dim equals the padded batch is per-row and gets
    sliced; anything else (a batch-level scalar metric) is handed to every
    request whole."""
    out = []
    for start, stop in offsets:
        vals = []
        for o in outputs:
            arr = np.asarray(o)
            if arr.ndim >= 1 and arr.shape[0] == padded_rows:
                vals.append(arr[start:stop])
            else:
                vals.append(arr)
        out.append(vals)
    return out


def build_batch(requests, buckets: Sequence[int]):
    """Concat + bucket + pad one coalesced pick in a single step,
    SURFACING the pad count instead of dropping it on the floor (ISSUE
    16 satellite): returns `(padded_feeds, rows, bucket, pad_rows)` so
    the server can attribute pad waste per bucket (`serving.pad_rows`
    counter, `serving.bucket[N].pad_frac` gauges) and stamp it into each
    member request's `batch_build` span."""
    feeds = concat_feeds([r.feeds for r in requests])
    rows = sum(r.rows for r in requests)
    bucket = bucket_for(rows, buckets)
    return pad_feeds(feeds, bucket), rows, bucket, bucket - rows


def coalesce(requests, max_rows: int):
    """Greedy continuous-batching pick: from a FIFO snapshot of queued
    requests, take the head request's model and every later request for
    the SAME model that still fits under `max_rows` total.  Returns
    (model, picked_requests); requests not picked keep their queue order.
    Head-of-line requests of OTHER models are untouched — the caller's
    next loop iteration serves them."""
    head = requests[0]
    picked = [head]
    total = head.rows
    for r in list(requests)[1:]:
        if r.model != head.model:
            continue
        if total + r.rows > max_rows:
            break
        picked.append(r)
        total += r.rows
    return head.model, picked
