"""The serving runtime: bounded request queue -> continuous batches.

`Server` is the robustness layer between callers and `Predictor`:

  * admission control — the queue is BOUNDED (`max_queue`, default
    FLAGS_serving_max_queue).  A submit past the bound is shed
    immediately with `ServingError(reason="overload")`: under sustained
    overload the queue depth (and therefore queueing latency) stays
    constant and the overflow is an explicit, counted signal
    (`serving.shed`) instead of an unbounded latency ramp.  The
    `bench.py --serve` overload arm proves p99 stays bounded this way.

  * per-request deadlines — `submit(deadline_ms=...)` (default
    FLAGS_serving_default_deadline_ms; 0 = none).  A request still
    queued when its deadline passes is cancelled with
    `ServingError(reason="timeout")` at batch-build time and the batch
    proceeds without it; a request picked up in time is always served
    to completion (mid-flight XLA execution is not cancellable).

  * continuous batching — worker threads drain the FIFO, coalesce
    same-model requests up to the largest bucket, pad to the next
    compiled bucket (batcher.py), run ONE predictor call, and split the
    outputs back per request.  Novel request sizes therefore never
    compile: models are warmed per bucket at load, and
    `executor.recompile` staying flat in steady state is an acceptance
    gate.

  * observability — everything rides the monitor: counters
    (serving.requests/completed/shed/timeouts/errors/batches/rows),
    lazy gauges (`serving.queue_depth`, `serving.p50_ms`,
    `serving.p99_ms`), per-bucket occupancy observations
    (`serving.bucket[N].occupancy`), one `serving_batch` record per
    executed batch and one `serving_event` per shed/timeout/reload —
    all exported through the existing Prometheus / JSON / JSONL paths
    and gated by `perf_report --check --max-shed-frac/--max-p99-ms`.

  * request-flight tracing (ISSUE 16) — with the monitor enabled, every
    submit gets a trace id and a span tree (`admission -> queue ->
    batch_build -> device -> fetch -> respond`; serving/tracing.py)
    recorded into the monitor's bounded trace ring as a `serving_trace`
    record.  EVERY terminal outcome closes its trace with the same
    stable reason code the raised `ServingError` carries — completed,
    shed, timeout, error, shutdown, and the admission-door rejections —
    so the ledger identity reconciles in the trace stream too
    (`tools/serve_trace.py --check`).  On top of it: pad-waste
    attribution (`serving.pad_rows` counter,
    `serving.bucket[N].pad_frac` gauges), queue-wait-fraction
    attribution (`serving.queue_wait_frac` gauge, per-batch
    `queue_wait_frac` on `serving_batch` records), windowed SLO burn
    accounting against the request deadlines
    (`serving.slo_good/slo_bad` counters, `serving.slo_good_frac` /
    `serving.slo_burn_rate` gauges vs FLAGS_serving_slo_target), and
    slow/bad-request exemplars captured into the flight-recorder black
    box on deadline/shed/error episodes.

Server-local stats (`stats()`) are tracked unconditionally so admission,
SLO, and pad/queue attribution accounting stay exact even with the
monitor disabled; the monitor counters mirror them when enabled.  The
trace layer itself follows the PR-8 disabled-mode contract: one branch
returning the shared NULL_TRACE, no allocation.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core import locks
from ..errors import ServingError, classify
from ..flags import flag as _flag
from ..monitor import MONITOR as _MON
from . import batcher as _bk
from . import publisher as _pub
from . import tracing as _tr
from .registry import ModelRegistry

__all__ = ["Future", "Server"]


class Future:
    """Completion handle for one submitted request."""

    __slots__ = ("_ev", "_result", "_exc", "t_enqueue")

    def __init__(self):
        self._ev = threading.Event()
        self._result = None
        self._exc = None
        self.t_enqueue = time.monotonic()

    def done(self) -> bool:
        return self._ev.is_set()

    def set_result(self, result):
        if not self._ev.is_set():  # first completion wins
            self._result = result
            self._ev.set()

    def set_exception(self, exc: BaseException):
        if not self._ev.is_set():
            self._exc = exc
            self._ev.set()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        if not self._ev.wait(timeout):
            raise TimeoutError("serving Future.result: not done yet")
        if self._exc is not None:
            raise self._exc
        return self._result

    def exception(self, timeout: Optional[float] = None):
        self._ev.wait(timeout)
        return self._exc


class _Request:
    __slots__ = ("model", "feeds", "rows", "deadline", "future", "trace",
                 "t_dequeue")

    def __init__(self, model, feeds, rows, deadline, future,
                 trace=_tr.NULL_TRACE):
        self.model = model
        self.feeds = feeds
        self.rows = rows
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.future = future
        self.trace = trace        # NULL_TRACE when the monitor is off
        self.t_dequeue = 0.0      # monotonic at batch pick (queue end)


class Server:
    """Continuous-batching model server over a `ModelRegistry`.

        registry = serving.ModelRegistry()
        with serving.Server(registry, buckets=(1, 4, 8)) as srv:
            srv.load_model("m", "/models/m")           # warms every bucket
            out = srv.infer("m", {"x": batch})          # sync
            fut = srv.submit("m", {"x": batch}, deadline_ms=50)
            srv.publish("m", ckpt_manager)              # verified hot reload
    """

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 buckets=None, max_queue: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 workers: int = 1, start: bool = True):
        self.registry = registry if registry is not None else ModelRegistry()
        self.buckets = _bk.parse_buckets(buckets)
        self.max_queue = int(max_queue if max_queue is not None
                             else _flag("FLAGS_serving_max_queue"))
        if default_deadline_ms is None:
            default_deadline_ms = _flag("FLAGS_serving_default_deadline_ms")
        self.default_deadline_ms = float(default_deadline_ms or 0.0)
        # SLO target: the fraction of SLO-tracked requests that must be
        # good; burn rate = bad_frac / (1 - target), so 1.0 means the run
        # is burning its error budget exactly as fast as the SLO allows
        self.slo_target = min(max(
            float(_flag("FLAGS_serving_slo_target") or 0.0), 0.0), 0.9999)
        self._n_workers = max(int(workers), 1)
        self._q: collections.deque = collections.deque()
        self._cv = locks.named_condition("serving.server", rank=12)
        self._threads: List[threading.Thread] = []
        self._running = False
        # accepting from construction: a not-yet-started server queues
        # (admission control still applies); workers drain once start()
        # runs.  stop() is what closes the door.
        self._accepting = True
        self._inflight = 0
        # server-local exact ledger (monitor counters mirror it when the
        # monitor is enabled; admission accounting must not depend on that)
        # ledger identity (at rest): requests == completed + shed +
        # timeouts + errors + shutdowns (`rejected` counts the
        # admission-door refusals that never enter `requests`; slo_good +
        # slo_bad covers every SLO-tracked terminal outcome)
        self._stats = {"requests": 0, "completed": 0, "shed": 0,
                       "timeouts": 0, "errors": 0, "shutdowns": 0,
                       "rejected": 0, "slo_good": 0, "slo_bad": 0,
                       "batches": 0, "rows": 0, "padded_rows": 0}
        self._lat_ms: collections.deque = collections.deque(maxlen=4096)
        # windowed SLO / queue-wait attribution (same sliding-window role
        # as _lat_ms): good/bad flags and (queue_s, total_s) samples
        self._slo_window: collections.deque = collections.deque(maxlen=4096)
        self._qwin: collections.deque = collections.deque(maxlen=4096)
        # per-bucket attribution ledger: bucket -> batches/requests/rows/
        # pad_rows/queue_s/total_s/infer_s (exact, unconditional; the
        # pad_frac gauges and bench.py's bucket_attribution read it)
        self._bucket_attr: Dict[int, dict] = {}
        # gauges close over a WEAK ref (the global monitor must not keep a
        # dead server — queue, latency window, registry — alive forever)
        # and are released by stop() if still ours; gauge names are
        # process-global, so with several servers the newest owner wins
        w = weakref.ref(self)
        self._gauge_fns = {
            "serving.queue_depth":
                lambda: (lambda s: float(len(s._q)) if s else 0.0)(w()),
            "serving.p50_ms":
                lambda: (lambda s: s._pct(50.0) if s else 0.0)(w()),
            "serving.p99_ms":
                lambda: (lambda s: s._pct(99.0) if s else 0.0)(w()),
            "serving.queue_wait_frac":
                lambda: (lambda s: s._queue_wait_frac_win() if s else 0.0)(w()),
            "serving.slo_good_frac":
                lambda: (lambda s: s._slo_good_frac() if s else 1.0)(w()),
            "serving.slo_burn_rate":
                lambda: (lambda s: s._slo_burn_rate() if s else 0.0)(w()),
        }
        # the bucket ladder is fixed at construction, so the per-bucket
        # pad-waste gauges can register up front (ISSUE 16 satellite)
        for b in self.buckets:
            self._gauge_fns[f"serving.bucket[{b}].pad_frac"] = (
                lambda bb=b: (lambda s: s._bucket_pad_frac(bb)
                              if s else 0.0)(w()))
        for n, f in self._gauge_fns.items():
            _MON.gauge(n).set_fn(f)
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._cv:
            if self._running:
                return self
            self._running = True
            self._accepting = True
        for i in range(self._n_workers):
            t = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"serving-worker-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Stop accepting; with `drain` (default) serve out everything
        already admitted first.  Requests still queued at a drain-less
        stop fail with reason="shutdown"."""
        deadline = time.monotonic() + timeout
        with self._cv:
            self._accepting = False
            if drain and self._threads:  # no workers -> nothing can drain
                while (self._q or self._inflight) and \
                        time.monotonic() < deadline:
                    self._cv.wait(0.05)
            self._running = False
            self._cv.notify_all()
            leftovers = list(self._q)
            self._q.clear()
        for r in leftovers:
            # the leftover died still queued: its open phase IS the queue
            self._finish_trace(r.trace, "shutdown", reason="shutdown",
                               final="queue")
            r.future.set_exception(ServingError(
                "server stopped before this request was served",
                reason="shutdown", model=r.model,
                trace_id=r.trace.trace_id))
        if leftovers:
            with self._cv:
                self._stats["shutdowns"] += len(leftovers)
                self._stats["slo_bad"] += len(leftovers)
                self._slo_window.extend(0.0 for _ in leftovers)
            _MON.counter("serving.shutdowns").inc(len(leftovers))
            _MON.counter("serving.slo_bad").inc(len(leftovers))
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        for n, f in self._gauge_fns.items():
            g = _MON.gauge(n)
            if g.fn is f:  # release only if a newer server hasn't taken over
                g.fn = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- model management (delegates) --------------------------------------
    def load_model(self, name: str, model_dir: str, config=None,
                   warm: bool = True):
        """Registry load; `warm` (default) compiles every serving bucket
        up front so first traffic never waits on XLA."""
        return self.registry.load(
            name, model_dir, config=config,
            warm_buckets=self.buckets if warm else None)

    def publish(self, name: str, src, warm: bool = True, **kw):
        """Verified hot reload (publisher.publish): staged verification,
        pre-swap bucket warm, atomic swap, old version retained."""
        kw.setdefault("warm_buckets", self.buckets if warm else ())
        return _pub.publish(self.registry, name, src, **kw)

    def rollback(self, name: str):
        return self.registry.rollback(name)

    # -- request path ------------------------------------------------------
    @staticmethod
    def _finish_trace(tr, outcome, reason=None, final=None, exemplar=False,
                      **annot):
        """Close a request's trace (idempotent — first close wins) and
        record it; `exemplar` additionally retains it in the black box's
        slow/bad-request ring.  No-op end to end on NULL_TRACE."""
        rec = tr.close(outcome, reason=reason, final=final, **annot)
        if rec is not None:
            _MON.record_trace(rec)
            if exemplar:
                _MON.record_exemplar(rec)
        return rec

    def submit(self, model: str, feeds: Dict[str, np.ndarray],
               deadline_ms: Optional[float] = None) -> Future:
        """Admit one request (all feeds batched on axis 0) or shed it.
        Sheds raise immediately — an overloaded server answers 'no' in
        O(1), it does not answer late.  Malformed requests (unknown
        model, wrong feed names/shapes, oversize) are rejected HERE so
        they can never poison the batch they would be coalesced into.
        Every terminal outcome — including the rejections this door
        raises — closes the request's trace with its reason code, and
        the raised ServingError carries the trace id."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        tr = _tr.maybe_trace(_MON, model,
                             deadline_ms=float(deadline_ms or 0.0) or None)
        try:
            version = self.registry.acquire(model)  # model_missing: the door
            rows = _bk.batch_rows(feeds)
            _bk.bucket_for(rows, self.buckets)  # oversize rejects at the door
            _bk.validate_feeds(feeds, version.feed_names,
                               version.program.global_block())
        except ServingError as e:
            e.trace_id = tr.trace_id
            self._finish_trace(tr, "rejected", reason=e.reason,
                               final="admission")
            with self._cv:
                self._stats["rejected"] += 1
            _MON.counter("serving.rejected").inc()
            raise
        tr.annotate(rows=rows)
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms and deadline_ms > 0 else None)
        fut = Future()
        req = _Request(model, feeds, rows, deadline, fut, tr)
        with self._cv:
            if not self._accepting:
                self._stats["rejected"] += 1
                _MON.counter("serving.rejected").inc()
                self._finish_trace(tr, "rejected", reason="shutdown",
                                   final="admission")
                raise ServingError("server is not accepting requests",
                                   reason="shutdown", model=model,
                                   trace_id=tr.trace_id)
            self._stats["requests"] += 1
            if len(self._q) >= self.max_queue:
                self._stats["shed"] += 1
                self._stats["slo_bad"] += 1
                self._slo_window.append(0.0)
                _MON.counter("serving.requests").inc()
                _MON.counter("serving.shed").inc()
                _MON.counter("serving.slo_bad").inc()
                _MON.record_step({"kind": "serving_event", "action": "shed",
                                  "model": model, "rows": rows,
                                  "queue_depth": len(self._q),
                                  "trace_id": tr.trace_id})
                self._finish_trace(tr, "shed", reason="overload",
                                   final="admission", exemplar=True,
                                   queue_depth=len(self._q))
                raise ServingError(
                    f"queue depth {len(self._q)} at the admission bound "
                    f"({self.max_queue}); request shed", reason="overload",
                    model=model, trace_id=tr.trace_id)
            tr.phase("admission")
            self._q.append(req)
            _MON.counter("serving.requests").inc()
            self._cv.notify()
        return fut

    def infer(self, model: str, feeds: Dict[str, np.ndarray],
              deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None) -> List[np.ndarray]:
        """Synchronous submit + wait."""
        return self.submit(model, feeds, deadline_ms).result(timeout)

    # -- worker ------------------------------------------------------------
    def _take_batch(self):
        """Under the lock: wait for work, then pick a same-model batch
        (FIFO head defines the model; batcher.coalesce fills up to the
        largest bucket)."""
        with self._cv:
            while self._running and not self._q:
                self._cv.wait(0.05)
            if not self._q:
                return None
            model, picked = _bk.coalesce(self._q, self.buckets[-1])
            now = time.monotonic()
            tq = time.perf_counter()  # one shared queue-end boundary
            for r in picked:
                self._q.remove(r)
                r.t_dequeue = now
                r.trace.phase("queue", t=tq)
            self._inflight += 1
            return model, picked

    def _expire(self, picked):
        """Split expired-vs-live at batch-build time; expired requests are
        cancelled (classified timeout) and the batch proceeds without
        them."""
        now = time.monotonic()
        live = []
        for r in picked:
            if r.deadline is not None and now > r.deadline:
                late_ms = round((now - r.deadline) * 1e3, 3)
                with self._cv:  # the ledger is exact even with N workers
                    self._stats["timeouts"] += 1
                    self._stats["slo_bad"] += 1
                    self._slo_window.append(0.0)
                _MON.counter("serving.timeouts").inc()
                _MON.counter("serving.slo_bad").inc()
                _MON.record_step({"kind": "serving_event",
                                  "action": "timeout", "model": r.model,
                                  "rows": r.rows, "late_ms": late_ms,
                                  "trace_id": r.trace.trace_id})
                self._finish_trace(r.trace, "timeout", reason="timeout",
                                   final="batch_build", exemplar=True,
                                   late_ms=late_ms)
                r.future.set_exception(ServingError(
                    f"deadline expired {round((now - r.deadline) * 1e3, 1)} ms "
                    f"before the request reached a batch", reason="timeout",
                    model=r.model, trace_id=r.trace.trace_id))
            else:
                live.append(r)
        return live

    def _worker_loop(self):
        while True:
            taken = self._take_batch()
            if taken is None:
                return
            model, picked = taken
            try:
                self._run_batch(model, picked)
            except BaseException as e:  # noqa: BLE001
                # a worker must survive ANYTHING (a logger's disk-full
                # OSError in record_step, a result-splitting bug): a dead
                # worker strands every future it picked and — at
                # workers=1 — wedges the whole server.  Fail the batch's
                # unresolved futures classified and keep serving.
                ce = classify(e)
                reason = getattr(ce, "reason", None) or type(ce).__name__
                n = sum(1 for r in picked if not r.future.done())
                for r in picked:
                    if not r.future.done():
                        self._finish_trace(r.trace, "error", reason=reason,
                                           final="error", exemplar=True)
                    r.future.set_exception(ce)
                if n:
                    with self._cv:
                        self._stats["errors"] += n
                        self._stats["slo_bad"] += n
                        self._slo_window.extend(0.0 for _ in range(n))
                    _MON.counter("serving.errors").inc(n)
                    _MON.counter("serving.slo_bad").inc(n)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _run_batch(self, model: str, picked):
        live = self._expire(picked)
        if not live:
            return
        t0p = time.perf_counter()
        try:
            # acquire ONCE per batch: a publish() swapping mid-batch never
            # touches us — this version object stays alive until we finish
            version = self.registry.acquire(model)
            padded, rows, bucket, pad_rows = _bk.build_batch(
                live, self.buckets)
            tb = time.perf_counter()  # batch built (shared phase boundary)
            for r in live:
                r.trace.phase("batch_build", t=tb)
                r.trace.annotate(bucket=bucket, pad_rows=pad_rows,
                                 batch_rows=rows)
            with _MON.span("serving.batch", model=model, bucket=bucket,
                           rows=rows, pad_rows=pad_rows):
                outs = version.run(padded)
            td = time.perf_counter()  # device done (dispatch+run+fetch of
            # the synchronous predictor fold into this one phase)
        except BaseException as e:
            ce = classify(e)
            reason = getattr(ce, "reason", None) or type(ce).__name__
            with self._cv:
                self._stats["errors"] += len(live)
                self._stats["slo_bad"] += len(live)
                self._slo_window.extend(0.0 for _ in live)
            _MON.counter("serving.errors").inc(len(live))
            _MON.counter("serving.slo_bad").inc(len(live))
            for r in live:
                self._finish_trace(r.trace, "error", reason=reason,
                                   final="error", exemplar=True)
                r.future.set_exception(ce)
            return
        offsets, at = [], 0
        for r in live:
            offsets.append((at, at + r.rows))
            at += r.rows
        per_req = _bk.split_rows(outs, offsets, bucket)
        tf = time.perf_counter()  # host-side result split done
        now = time.monotonic()
        lat_max = queue_ms_max = 0.0
        queue_s_sum = total_s_sum = 0.0
        good_flags, qwin_items, trace_recs = [], [], []
        for r, vals in zip(live, per_req):
            r.trace.phase("device", t=td)
            r.trace.phase("fetch", t=tf)
            r.future.set_result(vals)
            lat = (now - r.future.t_enqueue) * 1e3
            lat_max = max(lat_max, lat)
            self._lat_ms.append(lat)
            q_s = max(r.t_dequeue - r.future.t_enqueue, 0.0)
            tot_s = max(now - r.future.t_enqueue, 1e-9)
            queue_s_sum += q_s
            total_s_sum += tot_s
            queue_ms_max = max(queue_ms_max, q_s * 1e3)
            qwin_items.append((q_s, tot_s))
            # SLO accounting: a request with no deadline is good by
            # completing at all; one with a deadline must make it — a
            # picked-in-time request that finished LATE burns budget too
            good = r.deadline is None or now <= r.deadline
            good_flags.append(good)
            rec = r.trace.close("completed", lat_ms=round(lat, 3),
                                queue_ms=round(q_s * 1e3, 3),
                                slo_miss=not good)
            if rec is not None:
                trace_recs.append((rec, not good))
        good_n = sum(good_flags)
        t_build_s = tb - t0p
        t_infer_s = td - tb
        t_fetch_s = tf - td
        with self._cv:
            self._stats["completed"] += len(live)
            self._stats["batches"] += 1
            self._stats["rows"] += rows
            self._stats["padded_rows"] += pad_rows
            self._stats["slo_good"] += good_n
            self._stats["slo_bad"] += len(live) - good_n
            self._slo_window.extend(1.0 if g else 0.0 for g in good_flags)
            self._qwin.extend(qwin_items)
            a = self._bucket_attr.setdefault(
                bucket, {"batches": 0, "requests": 0, "rows": 0,
                         "pad_rows": 0, "queue_s": 0.0, "total_s": 0.0,
                         "infer_s": 0.0})
            a["batches"] += 1
            a["requests"] += len(live)
            a["rows"] += rows
            a["pad_rows"] += pad_rows
            a["queue_s"] += queue_s_sum
            a["total_s"] += total_s_sum
            a["infer_s"] += t_infer_s
        _MON.counter("serving.completed").inc(len(live))
        _MON.counter("serving.batches").inc()
        _MON.counter("serving.rows").inc(rows)
        _MON.counter("serving.padded_rows").inc(pad_rows)
        # `serving.pad_rows` is the documented pad-waste counter (ISSUE 16
        # satellite); `padded_rows` stays for older dashboards/gates
        _MON.counter("serving.pad_rows").inc(pad_rows)
        _MON.counter("serving.slo_good").inc(good_n)
        if len(live) - good_n:
            _MON.counter("serving.slo_bad").inc(len(live) - good_n)
        occupancy = rows / bucket
        _MON.observe(f"serving.bucket[{bucket}].occupancy", occupancy)
        for rec, slo_miss in trace_recs:
            _MON.record_trace(rec)
            if slo_miss:  # completed, but late: an SLO-burn exemplar
                _MON.record_exemplar(rec)
        record = {
            "kind": "serving_batch", "model": model, "bucket": bucket,
            "rows": rows, "requests": len(live),
            "pad_rows": pad_rows, "pad_frac": round(pad_rows / bucket, 4),
            "occupancy": round(occupancy, 4),
            "t_build_s": round(t_build_s, 6),
            "t_infer_s": round(t_infer_s, 6),
            "t_fetch_s": round(t_fetch_s, 6),
            "queue_ms_mean": round(queue_s_sum * 1e3 / len(live), 3),
            "queue_ms_max": round(queue_ms_max, 3),
            "queue_wait_frac": round(queue_s_sum / total_s_sum, 4)
            if total_s_sum > 0 else 0.0,
            "lat_ms_max": round(lat_max, 3),
            "queue_depth": len(self._q)}
        if live[0].trace.enabled:
            record["trace_ids"] = [r.trace.trace_id for r in live[:32]]
        _MON.record_step(record)

    # -- stats -------------------------------------------------------------
    def _pct(self, q: float) -> float:
        lat = list(self._lat_ms)
        if not lat:
            return 0.0
        return float(np.percentile(np.asarray(lat), q))

    def _slo_good_frac(self) -> float:
        win = list(self._slo_window)
        return (sum(win) / len(win)) if win else 1.0

    def _slo_burn_rate(self) -> float:
        denom = 1.0 - self.slo_target
        if denom <= 0:
            return 0.0
        return (1.0 - self._slo_good_frac()) / denom

    def _queue_wait_frac_win(self) -> float:
        win = list(self._qwin)
        tot = sum(t for _, t in win)
        return (sum(q for q, _ in win) / tot) if tot > 0 else 0.0

    def _bucket_pad_frac(self, bucket: int) -> float:
        a = self._bucket_attr.get(bucket)
        if not a:
            return 0.0
        denom = a["rows"] + a["pad_rows"]
        return a["pad_rows"] / denom if denom else 0.0

    def queue_wait_frac(self) -> float:
        """Lifetime queue-wait fraction: of all the wall time completed
        requests spent in the server, the share spent QUEUED (the
        gauge's sliding-window cousin; bench.py embeds this one)."""
        with self._cv:
            q = sum(a["queue_s"] for a in self._bucket_attr.values())
            t = sum(a["total_s"] for a in self._bucket_attr.values())
        return q / t if t > 0 else 0.0

    def bucket_attribution(self) -> Dict[int, dict]:
        """Per-bucket latency/pad attribution from the exact server-local
        ledger: where each bucket's wall time went (queued vs on device)
        and how much of its compute was pad waste.  The `bench.py
        --serve` record embeds this."""
        with self._cv:
            attr = {b: dict(a) for b, a in self._bucket_attr.items()}
        out = {}
        for b, a in sorted(attr.items()):
            denom = a["rows"] + a["pad_rows"]
            out[b] = {
                "batches": a["batches"], "requests": a["requests"],
                "rows": a["rows"], "pad_rows": a["pad_rows"],
                "pad_frac": round(a["pad_rows"] / denom, 4) if denom else 0.0,
                "occupancy": round(a["rows"] / denom, 4) if denom else 0.0,
                "queue_ms_mean": round(
                    a["queue_s"] * 1e3 / max(a["requests"], 1), 3),
                "infer_ms_mean": round(
                    a["infer_s"] * 1e3 / max(a["batches"], 1), 3),
                "queue_wait_frac": round(a["queue_s"] / a["total_s"], 4)
                if a["total_s"] > 0 else 0.0,
            }
        return out

    def latency_ms(self) -> Dict[str, float]:
        return {"p50": round(self._pct(50.0), 3),
                "p99": round(self._pct(99.0), 3),
                "samples": len(self._lat_ms)}

    def ledger(self) -> dict:
        """The exact request ledger plus its at-rest identity verdict —
        the chaos-campaign invariant probe (ISSUE 20).  `requests ==
        completed + shed + timeouts + errors + shutdowns` holds whenever
        no request is in flight (`rejected` counts admission-door
        refusals that never enter `requests`); `balanced` evaluates it
        so callers need not re-derive the identity."""
        with self._cv:
            s = dict(self._stats)
        out = {k: s[k] for k in ("requests", "completed", "shed",
                                 "timeouts", "errors", "shutdowns",
                                 "rejected")}
        out["balanced"] = (
            out["requests"] == out["completed"] + out["shed"]
            + out["timeouts"] + out["errors"] + out["shutdowns"])
        return out

    def stats(self) -> dict:
        with self._cv:
            s = dict(self._stats)
        s["queue_depth"] = len(self._q)
        s["pad_rows"] = s["padded_rows"]  # the documented alias
        s["queue_wait_frac"] = round(self.queue_wait_frac(), 4)
        s["slo"] = {"target": self.slo_target,
                    "good": s["slo_good"], "bad": s["slo_bad"],
                    "good_frac": round(self._slo_good_frac(), 4),
                    "burn_rate": round(self._slo_burn_rate(), 4)}
        s.update({f"lat_{k}_ms" if k != "samples" else "lat_samples": v
                  for k, v in self.latency_ms().items()})
        s["models"] = self.registry.models()
        return s
