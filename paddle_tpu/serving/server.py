"""The serving runtime: bounded request queue -> continuous batches.

`Server` is the robustness layer between callers and `Predictor`:

  * admission control — the queue is BOUNDED (`max_queue`, default
    FLAGS_serving_max_queue).  A submit past the bound is shed
    immediately with `ServingError(reason="overload")`: under sustained
    overload the queue depth (and therefore queueing latency) stays
    constant and the overflow is an explicit, counted signal
    (`serving.shed`) instead of an unbounded latency ramp.  The
    `bench.py --serve` overload arm proves p99 stays bounded this way.

  * per-request deadlines — `submit(deadline_ms=...)` (default
    FLAGS_serving_default_deadline_ms; 0 = none).  A request still
    queued when its deadline passes is cancelled with
    `ServingError(reason="timeout")` at batch-build time and the batch
    proceeds without it; a request picked up in time is always served
    to completion (mid-flight XLA execution is not cancellable).

  * continuous batching — worker threads drain the FIFO, coalesce
    same-model requests up to the largest bucket, pad to the next
    compiled bucket (batcher.py), run ONE predictor call, and split the
    outputs back per request.  Novel request sizes therefore never
    compile: models are warmed per bucket at load, and
    `executor.recompile` staying flat in steady state is an acceptance
    gate.

  * observability — everything rides the monitor: counters
    (serving.requests/completed/shed/timeouts/errors/batches/rows),
    lazy gauges (`serving.queue_depth`, `serving.p50_ms`,
    `serving.p99_ms`), per-bucket occupancy observations
    (`serving.bucket[N].occupancy`), one `serving_batch` record per
    executed batch and one `serving_event` per shed/timeout/reload —
    all exported through the existing Prometheus / JSON / JSONL paths
    and gated by `perf_report --check --max-shed-frac/--max-p99-ms`.

Server-local stats (`stats()`) are tracked unconditionally so admission
accounting stays exact even with the monitor disabled; the monitor
counters mirror them when enabled.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core import locks
from ..errors import ServingError, classify
from ..flags import flag as _flag
from ..monitor import MONITOR as _MON
from . import batcher as _bk
from . import publisher as _pub
from .registry import ModelRegistry

__all__ = ["Future", "Server"]


class Future:
    """Completion handle for one submitted request."""

    __slots__ = ("_ev", "_result", "_exc", "t_enqueue")

    def __init__(self):
        self._ev = threading.Event()
        self._result = None
        self._exc = None
        self.t_enqueue = time.monotonic()

    def done(self) -> bool:
        return self._ev.is_set()

    def set_result(self, result):
        if not self._ev.is_set():  # first completion wins
            self._result = result
            self._ev.set()

    def set_exception(self, exc: BaseException):
        if not self._ev.is_set():
            self._exc = exc
            self._ev.set()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        if not self._ev.wait(timeout):
            raise TimeoutError("serving Future.result: not done yet")
        if self._exc is not None:
            raise self._exc
        return self._result

    def exception(self, timeout: Optional[float] = None):
        self._ev.wait(timeout)
        return self._exc


class _Request:
    __slots__ = ("model", "feeds", "rows", "deadline", "future")

    def __init__(self, model, feeds, rows, deadline, future):
        self.model = model
        self.feeds = feeds
        self.rows = rows
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.future = future


class Server:
    """Continuous-batching model server over a `ModelRegistry`.

        registry = serving.ModelRegistry()
        with serving.Server(registry, buckets=(1, 4, 8)) as srv:
            srv.load_model("m", "/models/m")           # warms every bucket
            out = srv.infer("m", {"x": batch})          # sync
            fut = srv.submit("m", {"x": batch}, deadline_ms=50)
            srv.publish("m", ckpt_manager)              # verified hot reload
    """

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 buckets=None, max_queue: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 workers: int = 1, start: bool = True):
        self.registry = registry if registry is not None else ModelRegistry()
        self.buckets = _bk.parse_buckets(buckets)
        self.max_queue = int(max_queue if max_queue is not None
                             else _flag("FLAGS_serving_max_queue"))
        if default_deadline_ms is None:
            default_deadline_ms = _flag("FLAGS_serving_default_deadline_ms")
        self.default_deadline_ms = float(default_deadline_ms or 0.0)
        self._n_workers = max(int(workers), 1)
        self._q: collections.deque = collections.deque()
        self._cv = locks.named_condition("serving.server", rank=12)
        self._threads: List[threading.Thread] = []
        self._running = False
        # accepting from construction: a not-yet-started server queues
        # (admission control still applies); workers drain once start()
        # runs.  stop() is what closes the door.
        self._accepting = True
        self._inflight = 0
        # server-local exact ledger (monitor counters mirror it when the
        # monitor is enabled; admission accounting must not depend on that)
        # ledger identity (at rest): requests == completed + shed +
        # timeouts + errors + shutdowns
        self._stats = {"requests": 0, "completed": 0, "shed": 0,
                       "timeouts": 0, "errors": 0, "shutdowns": 0,
                       "batches": 0, "rows": 0, "padded_rows": 0}
        self._lat_ms: collections.deque = collections.deque(maxlen=4096)
        # gauges close over a WEAK ref (the global monitor must not keep a
        # dead server — queue, latency window, registry — alive forever)
        # and are released by stop() if still ours; gauge names are
        # process-global, so with several servers the newest owner wins
        w = weakref.ref(self)
        self._gauge_fns = {
            "serving.queue_depth":
                lambda: (lambda s: float(len(s._q)) if s else 0.0)(w()),
            "serving.p50_ms":
                lambda: (lambda s: s._pct(50.0) if s else 0.0)(w()),
            "serving.p99_ms":
                lambda: (lambda s: s._pct(99.0) if s else 0.0)(w()),
        }
        for n, f in self._gauge_fns.items():
            _MON.gauge(n).set_fn(f)
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._cv:
            if self._running:
                return self
            self._running = True
            self._accepting = True
        for i in range(self._n_workers):
            t = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"serving-worker-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Stop accepting; with `drain` (default) serve out everything
        already admitted first.  Requests still queued at a drain-less
        stop fail with reason="shutdown"."""
        deadline = time.monotonic() + timeout
        with self._cv:
            self._accepting = False
            if drain and self._threads:  # no workers -> nothing can drain
                while (self._q or self._inflight) and \
                        time.monotonic() < deadline:
                    self._cv.wait(0.05)
            self._running = False
            self._cv.notify_all()
            leftovers = list(self._q)
            self._q.clear()
        for r in leftovers:
            r.future.set_exception(ServingError(
                "server stopped before this request was served",
                reason="shutdown", model=r.model))
        if leftovers:
            with self._cv:
                self._stats["shutdowns"] += len(leftovers)
            _MON.counter("serving.shutdowns").inc(len(leftovers))
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        for n, f in self._gauge_fns.items():
            g = _MON.gauge(n)
            if g.fn is f:  # release only if a newer server hasn't taken over
                g.fn = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- model management (delegates) --------------------------------------
    def load_model(self, name: str, model_dir: str, config=None,
                   warm: bool = True):
        """Registry load; `warm` (default) compiles every serving bucket
        up front so first traffic never waits on XLA."""
        return self.registry.load(
            name, model_dir, config=config,
            warm_buckets=self.buckets if warm else None)

    def publish(self, name: str, src, warm: bool = True, **kw):
        """Verified hot reload (publisher.publish): staged verification,
        pre-swap bucket warm, atomic swap, old version retained."""
        kw.setdefault("warm_buckets", self.buckets if warm else ())
        return _pub.publish(self.registry, name, src, **kw)

    def rollback(self, name: str):
        return self.registry.rollback(name)

    # -- request path ------------------------------------------------------
    def submit(self, model: str, feeds: Dict[str, np.ndarray],
               deadline_ms: Optional[float] = None) -> Future:
        """Admit one request (all feeds batched on axis 0) or shed it.
        Sheds raise immediately — an overloaded server answers 'no' in
        O(1), it does not answer late.  Malformed requests (unknown
        model, wrong feed names/shapes, oversize) are rejected HERE so
        they can never poison the batch they would be coalesced into."""
        version = self.registry.acquire(model)  # model_missing at the door
        rows = _bk.batch_rows(feeds)
        _bk.bucket_for(rows, self.buckets)  # oversize rejects at the door
        _bk.validate_feeds(feeds, version.feed_names,
                           version.program.global_block())
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms and deadline_ms > 0 else None)
        fut = Future()
        req = _Request(model, feeds, rows, deadline, fut)
        with self._cv:
            if not self._accepting:
                raise ServingError("server is not accepting requests",
                                   reason="shutdown", model=model)
            self._stats["requests"] += 1
            if len(self._q) >= self.max_queue:
                self._stats["shed"] += 1
                _MON.counter("serving.requests").inc()
                _MON.counter("serving.shed").inc()
                _MON.record_step({"kind": "serving_event", "action": "shed",
                                  "model": model, "rows": rows,
                                  "queue_depth": len(self._q)})
                raise ServingError(
                    f"queue depth {len(self._q)} at the admission bound "
                    f"({self.max_queue}); request shed", reason="overload",
                    model=model)
            self._q.append(req)
            _MON.counter("serving.requests").inc()
            self._cv.notify()
        return fut

    def infer(self, model: str, feeds: Dict[str, np.ndarray],
              deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None) -> List[np.ndarray]:
        """Synchronous submit + wait."""
        return self.submit(model, feeds, deadline_ms).result(timeout)

    # -- worker ------------------------------------------------------------
    def _take_batch(self):
        """Under the lock: wait for work, then pick a same-model batch
        (FIFO head defines the model; batcher.coalesce fills up to the
        largest bucket)."""
        with self._cv:
            while self._running and not self._q:
                self._cv.wait(0.05)
            if not self._q:
                return None
            model, picked = _bk.coalesce(self._q, self.buckets[-1])
            for r in picked:
                self._q.remove(r)
            self._inflight += 1
            return model, picked

    def _expire(self, picked):
        """Split expired-vs-live at batch-build time; expired requests are
        cancelled (classified timeout) and the batch proceeds without
        them."""
        now = time.monotonic()
        live = []
        for r in picked:
            if r.deadline is not None and now > r.deadline:
                with self._cv:  # the ledger is exact even with N workers
                    self._stats["timeouts"] += 1
                _MON.counter("serving.timeouts").inc()
                _MON.record_step({"kind": "serving_event",
                                  "action": "timeout", "model": r.model,
                                  "rows": r.rows,
                                  "late_ms": round((now - r.deadline) * 1e3, 3)})
                r.future.set_exception(ServingError(
                    f"deadline expired {round((now - r.deadline) * 1e3, 1)} ms "
                    f"before the request reached a batch", reason="timeout",
                    model=r.model))
            else:
                live.append(r)
        return live

    def _worker_loop(self):
        while True:
            taken = self._take_batch()
            if taken is None:
                return
            model, picked = taken
            try:
                self._run_batch(model, picked)
            except BaseException as e:  # noqa: BLE001
                # a worker must survive ANYTHING (a logger's disk-full
                # OSError in record_step, a result-splitting bug): a dead
                # worker strands every future it picked and — at
                # workers=1 — wedges the whole server.  Fail the batch's
                # unresolved futures classified and keep serving.
                ce = classify(e)
                n = sum(1 for r in picked if not r.future.done())
                for r in picked:
                    r.future.set_exception(ce)
                if n:
                    with self._cv:
                        self._stats["errors"] += n
                    _MON.counter("serving.errors").inc(n)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _run_batch(self, model: str, picked):
        live = self._expire(picked)
        if not live:
            return
        t0 = time.monotonic()
        try:
            # acquire ONCE per batch: a publish() swapping mid-batch never
            # touches us — this version object stays alive until we finish
            version = self.registry.acquire(model)
            feeds = _bk.concat_feeds([r.feeds for r in live])
            rows = sum(r.rows for r in live)
            bucket = _bk.bucket_for(rows, self.buckets)
            padded = _bk.pad_feeds(feeds, bucket)
            with _MON.span("serving.batch", model=model, bucket=bucket,
                           rows=rows):
                outs = version.run(padded)
        except BaseException as e:
            ce = classify(e)
            with self._cv:
                self._stats["errors"] += len(live)
            _MON.counter("serving.errors").inc(len(live))
            for r in live:
                r.future.set_exception(ce)
            return
        offsets, at = [], 0
        for r in live:
            offsets.append((at, at + r.rows))
            at += r.rows
        per_req = _bk.split_rows(outs, offsets, bucket)
        now = time.monotonic()
        lat_max = 0.0
        for r, vals in zip(live, per_req):
            r.future.set_result(vals)
            lat = (now - r.future.t_enqueue) * 1e3
            lat_max = max(lat_max, lat)
            self._lat_ms.append(lat)
        with self._cv:
            self._stats["completed"] += len(live)
            self._stats["batches"] += 1
            self._stats["rows"] += rows
            self._stats["padded_rows"] += bucket - rows
        _MON.counter("serving.completed").inc(len(live))
        _MON.counter("serving.batches").inc()
        _MON.counter("serving.rows").inc(rows)
        _MON.counter("serving.padded_rows").inc(bucket - rows)
        occupancy = rows / bucket
        _MON.observe(f"serving.bucket[{bucket}].occupancy", occupancy)
        _MON.record_step({
            "kind": "serving_batch", "model": model, "bucket": bucket,
            "rows": rows, "requests": len(live),
            "occupancy": round(occupancy, 4),
            "t_infer_s": round(now - t0, 6),
            "lat_ms_max": round(lat_max, 3),
            "queue_depth": len(self._q)})

    # -- stats -------------------------------------------------------------
    def _pct(self, q: float) -> float:
        lat = list(self._lat_ms)
        if not lat:
            return 0.0
        return float(np.percentile(np.asarray(lat), q))

    def latency_ms(self) -> Dict[str, float]:
        return {"p50": round(self._pct(50.0), 3),
                "p99": round(self._pct(99.0), 3),
                "samples": len(self._lat_ms)}

    def stats(self) -> dict:
        with self._cv:
            s = dict(self._stats)
        s["queue_depth"] = len(self._q)
        s.update({f"lat_{k}_ms" if k != "samples" else "lat_samples": v
                  for k, v in self.latency_ms().items()})
        s["models"] = self.registry.models()
        return s
