"""Multi-model co-residency under an HBM budget.

The registry owns every loaded model's (program, scope, Predictor)
triple, versioned so the publisher (publisher.py) can swap a verified
new snapshot in atomically and keep the old version for instant
rollback.  Two robustness contracts live here:

  * ONE shared Executor for every model, version, and clone — the
    compiled-executable cache is keyed by (program, scope, feed
    signature), so N models aliasing one directory and N clones of one
    predictor hit the SAME cache entry per bucket shape and never
    compile N times (pinned by tests/test_serving.py's cache-share
    tests).

  * an HBM budget (FLAGS_serving_hbm_budget_mb or the constructor's
    override): before any device allocation the load is costed, in
    fallback order — (1) the static resource plan of the saved program
    at the largest bucket this load will warm (weights + activations +
    staged feeds, core/resource_plan.py `plan_model_bytes`); (2)
    manifest weight bytes (activations invisible); (3) nothing, in
    which case the load proceeds unbudgeted, the silent bypass is
    counted (`serving.unbudgeted_loads` + `unbudgeted_load` event) and
    only the post-load re-check can refuse.  A load past the budget
    first evicts cold models — least recently USED first, never the
    model being loaded — and, when eviction cannot free enough,
    refuses loudly with ServingError(reason="hbm_budget") instead of
    letting PJRT OOM the chip mid-request.  Live device usage is
    observable next to the ledger through the monitor/memstats gauges
    (`serving.hbm_used_mb` tracks the registry's ledger,
    `memory.device_bytes_in_use` the allocator's truth).

In-flight safety: `acquire()` hands out the active ModelVersion object;
a batch that holds one keeps serving from it even if an eviction,
unload, or publish replaces the registry entry mid-batch (Python
references keep the old version alive until the batch finishes) — the
zero-dropped-requests property the reload-under-load chaos test pins.
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core import locks
from ..core.dtypes import as_np_dtype
from ..core.executor import Executor, TPUPlace
from ..core.scope import Scope
from ..errors import ServingError
from ..flags import flag as _flag
from ..inference import AnalysisConfig, Predictor
from ..monitor import MONITOR as _MON
from . import tracing as _tr
from .. import io as _io

__all__ = ["ModelVersion", "ModelRegistry", "synthetic_feeds",
           "manifest_weight_bytes", "plan_model_bytes", "quant_manifest",
           "model_precision"]


def quant_manifest(model_dir: str) -> Optional[dict]:
    """The dir's __quant__.json (io.save_quantized_inference_model
    output) when it names at least one quantized weight, else None —
    None for plain float models AND for unreadable manifests (the load
    itself will fail loudly on the latter)."""
    try:
        with open(os.path.join(model_dir, _io.QUANT_MANIFEST)) as f:
            q = json.load(f)
        return q if q.get("weights") else None
    except (OSError, ValueError):
        return None


def model_precision(model_dir: str) -> str:
    """Serving-precision label for a model dir: "float32" for plain
    models; quantized dirs yield "int<bits>-><serve dtype>" from the
    quant manifest (e.g. "int8->bfloat16" — int8 grid numerics served
    as resident bf16 weights).  Mixed records join with "/"."""
    q = quant_manifest(model_dir)
    if q is None:
        return "float32"
    recs = list(q["weights"].values())
    bits = "/".join(str(b) for b in sorted(
        {int(r.get("bits", 8)) for r in recs}))
    dts = "/".join(sorted({str(r.get("dtype", "float32")) for r in recs}))
    return f"int{bits}->{dts}"


def _dtype_itemsize(name: str) -> int:
    try:
        return np.dtype(name or "float32").itemsize
    except TypeError:
        return 2  # bfloat16-class dtypes numpy can't name


def synthetic_feed_shapes(program, feed_names: Sequence[str], rows: int
                          ) -> Dict[str, tuple]:
    """THE bucket-shape rule, shared by warm-up feeds and the pre-load
    budget plan so the two can never diverge: batch dim -> `rows`, other
    dynamic (-1) dims -> 1."""
    block = program.global_block()
    shapes = {}
    for name in feed_names:
        var = block.var(name)
        shape = [int(d) for d in (var.shape or [])]
        if not shape:
            shape = [rows]
        else:
            shape = [1 if d < 0 else d for d in shape]
            shape[0] = int(rows)
        shapes[name] = tuple(shape)
    return shapes


def synthetic_feeds(program, feed_names: Sequence[str], rows: int,
                    seed: int = 0) -> Dict[str, np.ndarray]:
    """Deterministic warm-up/golden feeds shaped from the program's feed
    vars (`synthetic_feed_shapes`); float feeds get small positive values
    (0 sits on poles like log/1-over), int feeds get zeros (id 0 is
    always a valid row of any table)."""
    block = program.global_block()
    rng = np.random.RandomState(seed)
    feeds = {}
    for name, shape in synthetic_feed_shapes(program, feed_names,
                                             rows).items():
        var = block.var(name)
        dtype = as_np_dtype(var.dtype) or np.float32
        dtype = np.dtype(dtype)
        if dtype.kind in "iu":
            feeds[name] = np.zeros(shape, dtype)
        elif dtype.kind == "b":
            feeds[name] = np.zeros(shape, bool)
        else:
            feeds[name] = (rng.rand(*shape) * 0.1 + 0.05).astype(dtype)
    return feeds


def plan_model_bytes(model_dir: str, rows: int) -> int:
    """Pre-load HBM estimate from the STATIC RESOURCE PLAN of the saved
    program at the `rows`-row bucket shape: weights + live activations +
    staged feeds (core/resource_plan.py), i.e. what serving that bucket
    actually holds resident — not manifest weight bytes alone.  Reads only
    `__model__.json` (no weights touched).  Quantized dirs credit the
    weight narrowing: the plan prices weights at the program's dtype, but
    load_vars dequantizes quant-manifest weights into their SERVE dtype
    (e.g. bf16), so the plan estimate is reduced by the per-weight width
    difference.  0 when the program is absent/unplannable; callers fall
    back to `manifest_weight_bytes`."""
    try:
        with open(os.path.join(model_dir, _io.MODEL_FILENAME)) as f:
            doc = json.load(f)
        from ..core.program import Program
        from ..core.resource_plan import plan_program

        program = Program.from_dict(doc)
        feed_shapes = synthetic_feed_shapes(program, doc.get("feed_names", []),
                                            rows)
        plan = plan_program(program, feed_shapes, doc.get("fetch_names", []))
        total = int(plan.peak_bytes)
        qweights = (quant_manifest(model_dir) or {}).get("weights", {})
        if qweights:
            block = program.global_block()
            for wname, rec in qweights.items():
                try:
                    var = block.var(wname)
                except Exception:
                    continue
                elems = 1
                for d in (var.shape or []):
                    elems *= max(int(d), 1)
                orig = np.dtype(as_np_dtype(var.dtype) or np.float32).itemsize
                total -= elems * max(
                    orig - _dtype_itemsize(rec.get("dtype", "float32")), 0)
        return total
    except Exception:
        return 0


def manifest_weight_bytes(model_dir: str) -> int:
    """Pre-load HBM estimate from the model dir's manifest (shape x dtype
    per persistable) — the FALLBACK when the saved program cannot be
    planned (`plan_model_bytes`); activations and workspace are invisible
    to it.  Weights named by the dir's quant manifest are priced at their
    SERVE dtype (load_vars dequantizes int8 payloads into the per-weight
    "dtype" record), so a bf16-serving quantized model budgets at half
    its fp32 parent's weight bytes.  0 when the manifest is
    absent/unreadable (the load itself will fail loudly later — and the
    registry counts the unbudgeted load, see ModelRegistry.load)."""
    total = 0
    qweights = (quant_manifest(model_dir) or {}).get("weights", {})
    try:
        with open(os.path.join(model_dir, _io.MANIFEST)) as f:
            manifest = json.load(f)
        for entry in manifest.get("vars", []):
            n = 1
            for d in entry.get("shape", []):
                n *= max(int(d), 1)
            qrec = qweights.get(entry.get("name"))
            dtype = (qrec.get("dtype", "float32") if qrec
                     else entry.get("dtype", "float32"))
            total += n * _dtype_itemsize(dtype)
    except (OSError, ValueError, KeyError):
        return 0
    return total


class ModelVersion:
    """One immutable served version: program + weights scope + the
    predictor bound to the registry's shared executor."""

    _ids = iter(range(1, 1 << 62))

    def __init__(self, program, feed_names, fetch_names, scope: Scope,
                 predictor: Predictor, src: str,
                 precision: Optional[str] = None):
        self.version = next(ModelVersion._ids)
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.scope = scope
        self.predictor = predictor
        self.src = src
        # serving precision from the source dir's quant manifest
        # ("float32" / "int8->bfloat16" / ...), surfaced in load/publish
        # events and models() so an operator can see which precision a
        # version serves at
        self.precision = precision or model_precision(src)
        self.created_ts = time.time()
        self.bytes = self._weight_bytes()
        # per-thread predictor clones: a Predictor serializes on its own
        # lock, so N server workers hammering ONE predictor would execute
        # batches one at a time.  Clones share the weights AND the
        # compiled-executable cache (inference.Predictor.clone), so this
        # buys real parallelism at zero extra compiles; bounded by the
        # process's thread count.
        self._clones: Dict[int, Predictor] = {}
        self._clones_lock = locks.named_lock("serving.clones", rank=16)

    def _weight_bytes(self) -> int:
        total = 0
        for n in self.scope.local_var_names():
            v = self.scope.find_var(n)
            nb = getattr(v, "nbytes", None)
            if nb is None:
                try:
                    nb = np.asarray(v).nbytes
                except Exception:
                    nb = 0
            total += int(nb)
        return total

    def run(self, feeds, fetch_names=None):
        tid = threading.get_ident()
        p = self._clones.get(tid)
        if p is None:
            with self._clones_lock:
                p = self._clones.get(tid)
                if p is None:
                    # first thread serves from the base predictor; later
                    # threads get their own clone (clone-per-thread, the
                    # documented scaling contract)
                    p = (self.predictor if not self._clones
                         else self.predictor.clone())
                    self._clones[tid] = p
        return p.run(feeds, fetch_names=fetch_names)


class _Model:
    def __init__(self, name: str, version: ModelVersion):
        self.name = name
        self.versions: List[ModelVersion] = [version]
        self.active = version
        self.last_used = time.monotonic()
        # pinned while its load() is still warming: a concurrent load's
        # budget eviction must not yank a model out from under its own
        # warm-up (acquire() would raise model_missing from inside load)
        self.pinned = False


class ModelRegistry:
    def __init__(self, place=None, hbm_budget_mb: Optional[float] = None,
                 executor: Optional[Executor] = None, keep_versions: int = 2):
        self.place = place if place is not None else TPUPlace(0)
        # ONE executor == one compiled-executable cache for the whole
        # registry (models, published versions, clones)
        self.executor = executor if executor is not None else Executor(self.place)
        self._budget_mb = hbm_budget_mb
        self.keep_versions = max(int(keep_versions), 1)
        self._models: Dict[str, _Model] = {}
        self._lock = locks.named_rlock("serving.registry", rank=14)
        # serializes publish() ladders PER MODEL (publisher.py): two
        # concurrent publishes into one model would double-stage, double-
        # warm, and leave "prev version for rollback" pointing at the
        # LOSER's fresh version instead of the one traffic was on.  An
        # in-flight set under its own condition — NOT a lock held across
        # the ladder: staging+warm block on disk and XLA for seconds, and
        # nothing (not even another model's publish) should queue behind
        # that; losers wait on the condition, the ladder itself runs
        # lock-free
        self._publishing: set = set()
        self._publish_cv = locks.named_condition("serving.publish", rank=10)
        # verified-but-not-yet-active versions (two-phase fleet rolling
        # publish): {name: ModelVersion} held between the verify ladder
        # and the fleet-wide activate ack (serving/fleet.py)
        self._staged: Dict[str, ModelVersion] = {}
        # publish-rejected source dirs: repeated publishes of a snapshot
        # that already failed verification reject fast (publisher.py)
        self.quarantined: set = set()
        # weak ref: the global monitor's gauges must not pin a dead
        # registry (and every model scope it holds) for the process life
        w = weakref.ref(self)
        _MON.gauge("serving.models").set_fn(
            lambda: (lambda r: float(len(r._models)) if r else 0.0)(w()))
        _MON.gauge("serving.hbm_used_mb").set_fn(
            lambda: (lambda r: r.used_bytes() / 1e6 if r else 0.0)(w()))

    # -- budget ------------------------------------------------------------
    def budget_bytes(self) -> int:
        mb = self._budget_mb
        if mb is None:
            mb = _flag("FLAGS_serving_hbm_budget_mb")
        return int(float(mb or 0) * 1e6)

    def used_bytes(self) -> int:
        with self._lock:
            seen, total = set(), 0
            for m in self._models.values():
                for v in m.versions:
                    if id(v) not in seen:  # aliased dirs share versions
                        seen.add(id(v))
                        total += v.bytes
            return total

    def _event(self, action: str, **kw):
        _MON.record_step({"kind": "serving_event", "action": action, **kw})

    @staticmethod
    def _sparse_digest(version) -> Optional[str]:
        """Content digest over the version's SelectedRows vars (None when
        it holds no sparse state) — what this PROCESS actually loaded,
        stamped on load/activate events so `serve_trace --fleet --check`
        can reconcile it against the publisher's `sparse_digest` (ISSUE
        19: a torn or rotted sparse snapshot shows up as replicas
        disagreeing with the publish event)."""
        from .. import integrity as _integrity

        try:
            return _integrity.sparse_state_digest(version.scope)
        except Exception:
            return None

    def _make_room(self, need: int, loading: str):
        """Evict cold models (LRU, never `loading`) until `need` more
        bytes fit under the budget; classified refusal when they can't."""
        budget = self.budget_bytes()
        if not budget:
            return
        while self.used_bytes() + need > budget:
            victims = sorted(
                (m for n, m in self._models.items()
                 if n != loading and not m.pinned),
                key=lambda m: m.last_used)
            if not victims:
                raise ServingError(
                    f"loading {loading!r} needs ~{need/1e6:.1f} MB but the "
                    f"HBM budget is {budget/1e6:.1f} MB with "
                    f"{self.used_bytes()/1e6:.1f} MB resident and nothing "
                    f"left to evict — raise FLAGS_serving_hbm_budget_mb or "
                    f"shrink the model", reason="hbm_budget", model=loading)
            victim = victims[0]
            del self._models[victim.name]
            _MON.counter("serving.evictions").inc()
            self._event("evict", model=victim.name,
                        freed_bytes=sum(v.bytes for v in victim.versions),
                        for_model=loading)

    # -- loading -----------------------------------------------------------
    def load(self, name: str, model_dir: str,
             config: Optional[AnalysisConfig] = None,
             warm_buckets: Optional[Sequence[int]] = None) -> ModelVersion:
        """Load an inference-model dir (io.save_inference_model output)
        under `name`.  A dir already resident under another name is
        ALIASED — the new name shares the same ModelVersion (and so the
        same compiled executables and HBM bytes).  `warm_buckets`
        pre-compiles the given batch buckets so first traffic never
        waits on XLA."""
        real = os.path.realpath(model_dir)
        # budget estimate, in fallback order (documented contract):
        #   1. static resource plan at the LARGEST bucket this load will
        #      warm — weights + activations + staged feeds
        #      (core/resource_plan.py), what serving actually holds;
        #   2. manifest weight bytes — activations invisible;
        #   3. nothing — the load proceeds UNBUDGETED and only the
        #      post-load re-check below can refuse; that silent bypass is
        #      counted (serving.unbudgeted_loads) and recorded so an
        #      operator can see budget-blind loads instead of discovering
        #      them at the allocator.
        # Estimated OUTSIDE the lock: plan_model_bytes reads and plans the
        # saved program, which must never stall a serving worker's
        # acquire() (wasted only in the rare alias case).
        need = (plan_model_bytes(model_dir, max(warm_buckets))
                if warm_buckets else 0)
        if not need:
            need = manifest_weight_bytes(model_dir)
        with self._lock:
            alias = next((m for m in self._models.values()
                          if os.path.realpath(m.active.src) == real), None)
            if alias is not None:
                entry = _Model(name, alias.active)
                entry.versions = alias.versions
                entry.pinned = True
                self._models[name] = entry
                self._event("load", model=name, alias_of=alias.name,
                            version=alias.active.version)
                version = alias.active
            else:
                if not need and self.budget_bytes():
                    _MON.counter("serving.unbudgeted_loads").inc()
                    self._event("unbudgeted_load", model=name, src=model_dir)
                self._make_room(need, name)
        if alias is None:
            # the disk-heavy stage runs OUTSIDE the lock: acquire() from
            # serving workers (one per batch) must never stall behind a
            # cold model's weights streaming in
            cfg = config or AnalysisConfig(model_dir, place=self.place)
            predictor = Predictor(cfg, executor=self.executor)
            version = ModelVersion(predictor.program,
                                   predictor.feed_names,
                                   predictor.fetch_names,
                                   predictor.scope,
                                   predictor, src=model_dir)
            with self._lock:
                # estimate was from the manifest and other loads may have
                # landed meanwhile; the loaded truth may also differ
                # (quantized int8 on disk dequantizes to float) —
                # re-check and refuse rather than serve past the budget
                budget = self.budget_bytes()
                if budget and self.used_bytes() + version.bytes > budget:
                    self._make_room(version.bytes, name)
                    if self.used_bytes() + version.bytes > budget:
                        raise ServingError(
                            f"{name!r} loaded at {version.bytes/1e6:.1f} "
                            f"MB, past the {budget/1e6:.1f} MB budget "
                            f"even after eviction", reason="hbm_budget",
                            model=name)
                entry = _Model(name, version)
                entry.pinned = True  # not evictable until this load returns
                self._models[name] = entry
                _MON.counter("serving.model_loads").inc()
                self._event("load", model=name, version=version.version,
                            bytes=version.bytes, src=model_dir,
                            precision=version.precision,
                            sparse_digest=self._sparse_digest(version))
        try:
            if warm_buckets:
                # outside the lock: warming compiles, and acquire() from
                # serving workers must not block behind XLA (alias warms
                # are pure cache hits and cheap either way)
                self.warm(name, warm_buckets)
        finally:
            with self._lock:
                m = self._models.get(name)
                if m is not None:
                    m.pinned = False
        return version

    def warm(self, name: str, buckets: Sequence[int]) -> int:
        """Compile every bucket shape for `name`'s active version by
        running a synthetic batch through it (the load-time compile
        lane); returns the number of buckets run."""
        version = self.acquire(name)
        for b in sorted(set(int(b) for b in buckets)):
            with _MON.span("serving.warm", model=name, bucket=b):
                version.run(synthetic_feeds(version.program,
                                            version.feed_names, b))
        return len(set(buckets))

    # -- lookup / lifecycle ------------------------------------------------
    def acquire(self, name: str) -> ModelVersion:
        """The active version (bumps recency).  Hold the returned object
        for the whole batch: swaps/evictions never invalidate it."""
        with self._lock:
            m = self._models.get(name)
            if m is None:
                raise ServingError(f"no model {name!r} loaded "
                                   f"(loaded: {sorted(self._models)})",
                                   reason="model_missing", model=name)
            m.last_used = time.monotonic()
            return m.active

    def models(self) -> Dict[str, dict]:
        with self._lock:
            return {n: {"version": m.active.version,
                        "versions": [v.version for v in m.versions],
                        "bytes": m.active.bytes, "src": m.active.src,
                        "precision": m.active.precision}
                    for n, m in self._models.items()}

    def unload(self, name: str):
        with self._lock:
            m = self._models.pop(name, None)
        if m is None:
            raise ServingError(f"no model {name!r} to unload",
                               reason="model_missing", model=name)
        self._event("unload", model=name)

    # -- version swap (publisher.py drives this) ---------------------------
    def publish_version(self, name: str, version: ModelVersion) -> ModelVersion:
        """Atomically make `version` the served one; returns the previous
        active (retained for rollback, older history trimmed to
        keep_versions)."""
        with self._lock:
            m = self._models.get(name)
            if m is None:
                raise ServingError(f"no model {name!r} to publish into",
                                   reason="model_missing", model=name)
            prev = m.active
            m.versions.append(version)
            m.active = version
            if len(m.versions) > self.keep_versions:
                m.versions = m.versions[-self.keep_versions:]
            return prev

    # -- two-phase staged swap (fleet rolling publish, serving/fleet.py) ---
    def stage_version(self, name: str, version: ModelVersion) -> ModelVersion:
        """Hold a fully verified/warmed version WITHOUT swapping it in —
        phase one of the fleet's two-phase rolling publish: every replica
        verifies and warms the staged snapshot while the old version keeps
        serving, and nothing touches traffic until the fleet-wide
        `activate_staged` phase.  One staged slot per model; re-staging
        replaces the held version."""
        with self._lock:
            if name not in self._models:
                raise ServingError(f"no model {name!r} to stage into",
                                   reason="model_missing", model=name)
            self._staged[name] = version
        self._event("stage", model=name, version=version.version,
                    src=version.src)
        return version

    def staged(self, name: str) -> Optional[ModelVersion]:
        with self._lock:
            return self._staged.get(name)

    def activate_staged(self, name: str) -> ModelVersion:
        """Atomically swap the held staged version in as the served one
        (phase two).  The previous active is retained for rollback()."""
        with self._lock:
            version = self._staged.pop(name, None)
        if version is None:
            raise ServingError(
                f"model {name!r} has no staged version to activate — "
                f"stage_version/publish(stage_only=True) first",
                reason="model_missing", model=name)
        prev = self.publish_version(name, version)
        _MON.counter("serving.reloads").inc()
        self._event("activate_staged", model=name, version=version.version,
                    prev_version=prev.version, src=version.src,
                    sparse_digest=self._sparse_digest(version))
        return version

    def discard_staged(self, name: str) -> bool:
        """Drop a held staged version without ever serving it (a halted
        fleet roll converging back on the last good version).  Returns
        whether anything was held."""
        with self._lock:
            version = self._staged.pop(name, None)
        if version is not None:
            self._event("discard_staged", model=name,
                        version=version.version, src=version.src)
        return version is not None

    def rollback(self, name: str) -> ModelVersion:
        """Re-activate the retained previous version (instant: it is
        still loaded and its executables still cached)."""
        with self._lock:
            m = self._models.get(name)
            if m is None:
                raise ServingError(f"no model {name!r} to roll back",
                                   reason="model_missing", model=name)
            older = [v for v in m.versions if v is not m.active]
            if not older:
                raise ServingError(
                    f"model {name!r} has no retained previous version",
                    reason="model_missing", model=name)
            m.active = older[-1]
            _MON.counter("serving.rollbacks").inc()
            # control trace id: the rollback episode is addressable on the
            # request timeline (serve_trace) like a publish is
            self._event("rollback", model=name, version=m.active.version,
                        trace_id=_tr.control_trace_id("rb"))
            return m.active
