"""paddle_tpu.serving — robust serving runtime (ISSUE 11).

Continuous batching into pre-compiled pad-to-bucket shapes, per-request
deadlines, admission control with load shedding, multi-model
co-residency under an HBM budget, and verified hot model reload with
instant rollback — the serving half of the reference's ~29k-LoC
`paddle/fluid/inference` stack, built robustness-first on top of the
compiled-executable cache, `CheckpointManager`, and the monitor plane.

    from paddle_tpu import serving

    registry = serving.ModelRegistry(hbm_budget_mb=1024)
    with serving.Server(registry, buckets=(1, 4, 8, 16)) as srv:
        srv.load_model("ranker", "/models/ranker")     # warms every bucket
        out = srv.infer("ranker", {"x": batch})        # pads, never compiles
        srv.publish("ranker", ckpt_manager)            # verify -> swap
        srv.rollback("ranker")                         # instant undo

Failure semantics ride `paddle_tpu.errors.ServingError` (reason codes:
overload / timeout / oversize / publish_rejected / hbm_budget /
model_missing / shutdown); metrics ride the monitor (serving.* counters
and gauges, `serving_batch` / `serving_event` records) and are gated by
`perf_report --check --max-shed-frac/--max-p99-ms`.  With the monitor
enabled every request additionally carries a flight trace
(`serving_trace` records; tracing.py) inspectable live with
`tools/serve_trace.py`, plus SLO burn-rate and pad/queue attribution
gauges (ISSUE 16).  See docs/serving.md and docs/observability.md.

Fleet mode (ISSUE 18): `ServingFleet` supervises N replica Server
processes behind a health-aware `Router` (heartbeat membership,
least-inflight dispatch, exactly-once `replica_down` accounting) with
zero-downtime `rolling_publish` — verify everywhere via
`publish(stage_only=True)`, activate only after all acks, halt and
converge back on the last good version when a replica rejects or the
store faults mid-roll (reason codes replica_down / roll_halted; CLI
`python -m paddle_tpu.launch --serve`; merged fleet view
`tools/serve_trace.py --fleet`).
"""
from __future__ import annotations

from .batcher import (DEFAULT_BUCKETS, bucket_for, build_batch,  # noqa: F401
                      coalesce, concat_feeds, pad_feeds, parse_buckets,
                      split_rows, validate_feeds)
from .fleet import ServingFleet  # noqa: F401
from .publisher import (QUARANTINE_MARKER, publish,  # noqa: F401
                        quarantine_marker, rollback, verify_snapshot_dir)
from .router import Router  # noqa: F401
from .registry import (ModelRegistry, ModelVersion,  # noqa: F401
                       manifest_weight_bytes, model_precision,
                       plan_model_bytes, quant_manifest, synthetic_feeds)
from .server import Future, Server  # noqa: F401
from .tracing import (NULL_TRACE, RequestTrace, TRACE_PHASES,  # noqa: F401
                      control_trace_id, maybe_trace)

__all__ = [
    "DEFAULT_BUCKETS", "parse_buckets", "bucket_for", "pad_feeds",
    "concat_feeds", "split_rows", "coalesce", "validate_feeds",
    "build_batch",
    "ModelRegistry", "ModelVersion", "synthetic_feeds",
    "manifest_weight_bytes", "plan_model_bytes",
    "quant_manifest", "model_precision",
    "publish", "rollback", "verify_snapshot_dir",
    "QUARANTINE_MARKER", "quarantine_marker",
    "Server", "Future",
    "ServingFleet", "Router",
    "RequestTrace", "NULL_TRACE", "maybe_trace", "control_trace_id",
    "TRACE_PHASES",
]
