"""Health- and queue-depth-aware request routing for the serving fleet.

The router is the client-facing half of `paddle_tpu.serving.fleet`: it
holds no model and serves no traffic itself — it picks which replica
gets each request and keeps the exact fleet-side ledger the replicas'
own ledgers are reconciled against (`serve_trace --fleet --check`).

Dispatch policy (ISSUE 18):

  * candidates = replicas whose `FleetHealth` status is "alive" (a
    "draining" replica still finishes its in-flight work but takes no
    NEW traffic), that are not locally *suspect* (see below), and whose
    router-side inflight count is under `inflight_cap`;
  * among candidates, least-loaded wins: fewest router-side inflight,
    then the shallowest queue / lowest p99 from the replica's own beat
    telemetry (the monitor stream riding `ReplicaBeat` payloads);
  * no candidate at all is classified, not an exception soup:
    every candidate at its inflight cap -> `reason="overload"`
    (backpressure, retry later); no live replica -> `reason="replica_down"`.

Suspicion closes the heartbeat-staleness window: a TCP connect/request
failure marks the replica suspect IMMEDIATELY (with the beat seq it was
suspected at), so new traffic redistributes on the very next request
instead of waiting out `interval * miss_factor`.  The mark clears when
the beat sequence moves off the suspicion point — ADVANCED past it (a
live replica that dropped one connection gets traffic back within one
beat) or restarted BELOW it (a supervisor relaunch begins a fresh seq
space at 1; the dead incarnation's high-water mark must not bench the
new process).  The supervisor also clears the mark explicitly via
`note_restart` the moment it relaunches a rank.

Failure semantics per request:

  * connect refused/timed out BEFORE the request was written: nothing
    reached the replica, so the router retries the next candidate
    transparently (at most one pass over the fleet);
  * socket death AFTER the request was written (the replica died with
    this request in flight): the request fails classified
    `ServingError(reason="replica_down")` — the router cannot know
    whether it executed, so it never blind-retries it;
  * a classified refusal from the replica (overload/timeout/shutdown/..)
    is re-raised verbatim — backpressure must reach the client.

Wire protocol: one JSON object per line over a fresh TCP connection per
request (newline-delimited both ways; `replica_main.py` is the server
end).  Per-request connections keep the router lock-free around
sockets — every blocking call here runs outside the ledger lock.
"""
from __future__ import annotations

__all__ = ["Router", "rpc", "ConnectFailed",
           "encode_feeds", "decode_feeds",
           "encode_arrays", "decode_arrays"]

import copy
import json
import socket
import time
from typing import Dict, List, Optional

import numpy as np

from ..core import locks
from ..errors import ServingError
from ..monitor import MONITOR as _MON

# One pass over the fleet: a refused connect burns one retry, so the
# worst case (every replica died since the last beat) stays bounded.
_CONNECT_TIMEOUT_S = 5.0


# ---- wire encoding ----------------------------------------------------------

def _encode_array(a) -> dict:
    a = np.asarray(a)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "data": a.ravel().tolist()}


def _decode_array(d: dict) -> np.ndarray:
    return np.asarray(d["data"], dtype=d["dtype"]).reshape(d["shape"])


def encode_feeds(feeds: Dict[str, np.ndarray]) -> Dict[str, dict]:
    return {k: _encode_array(v) for k, v in feeds.items()}


def decode_feeds(doc: Dict[str, dict]) -> Dict[str, np.ndarray]:
    return {k: _decode_array(v) for k, v in doc.items()}


def encode_arrays(arrays) -> List[dict]:
    return [_encode_array(a) for a in arrays]


def decode_arrays(docs) -> List[np.ndarray]:
    return [_decode_array(d) for d in docs]


# ---- transport --------------------------------------------------------------

class ConnectFailed(ConnectionError):
    """The transport failed BEFORE the request reached the replica —
    the one transport failure a router may retry on another replica."""


def rpc(port: int, msg: dict, timeout_s: float = 30.0,
        host: str = "127.0.0.1") -> dict:
    """One request/reply over a fresh connection.  Raises ConnectFailed
    when the failure provably precedes delivery (safe to retry
    elsewhere) and plain OSError once the request may have executed."""
    payload = (json.dumps(msg) + "\n").encode("utf-8")
    try:
        s = socket.create_connection((host, port),
                                     timeout=_CONNECT_TIMEOUT_S)
    except OSError as e:
        raise ConnectFailed(f"connect to replica at :{port}: {e}") from e
    with s:
        s.settimeout(timeout_s)
        s.sendall(payload)
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError(
                    f"replica at :{port} closed the connection mid-reply")
            buf += chunk
    return json.loads(buf.decode("utf-8"))


class Router:
    """Dispatches requests across the fleet's live replicas.

        router = Router(health)          # a dist_resilience.FleetHealth
        out = router.infer("m", {"x": batch}, deadline_ms=50)
        router.stats()                   # the fleet-side exact ledger
    """

    def __init__(self, health, inflight_cap: int = 8,
                 rpc_timeout_s: float = 60.0):
        self.health = health
        self.inflight_cap = max(int(inflight_cap), 1)
        self.rpc_timeout_s = float(rpc_timeout_s)
        # ledger + dispatch state; every socket op runs OUTSIDE this lock
        self._lock = locks.named_lock("serving.router", rank=6)
        self._inflight: Dict[int, int] = {}
        self._suspect: Dict[int, Optional[int]] = {}  # rank -> seq@suspicion
        self._stats = {"requests": 0, "completed": 0, "errors": 0,
                       "retries": 0,
                       "by_reason": {}, "routed": {}}

    # -- candidate selection ------------------------------------------------
    def _mark_suspect(self, rank: int, seq: Optional[int]):
        with self._lock:
            self._suspect[rank] = seq
        _MON.counter("serving.fleet.suspects").inc()

    def note_restart(self, rank: int):
        """The supervisor relaunched this rank: suspicion was held
        against the DEAD incarnation's beat seq and does not transfer
        to the fresh process (whose seq space restarts at 1)."""
        with self._lock:
            self._suspect.pop(rank, None)

    def _pick(self, table: Dict[int, dict]) -> Optional[dict]:
        """Least-loaded live candidate, or a classified refusal.  `table`
        is a FleetHealth.poll() result (polled OUTSIDE the lock)."""
        with self._lock:
            candidates = []
            capped = 0
            for r, info in table.items():
                if info["status"] != "alive":
                    continue
                seq = info["seq"]
                if r in self._suspect:
                    at = self._suspect[r]
                    # forgiven when the beats advanced past the suspicion
                    # point — OR restarted BELOW it: a seq lower than the
                    # one we suspected at can only be a fresh incarnation
                    # (note_restart wiped the corpse's hb file and the
                    # new process counts from 1 again)
                    if seq is not None and (at is None or seq != at):
                        del self._suspect[r]
                    else:
                        continue
                tel = info.get("tel") or {}
                if "port" not in tel:
                    continue  # beating but not yet listening
                inflight = self._inflight.get(r, 0)
                if inflight >= self.inflight_cap:
                    capped += 1
                    continue
                candidates.append((inflight, tel.get("q", 0),
                                   tel.get("p99", 0.0), r, tel))
            if not candidates:
                if capped:
                    raise ServingError(
                        f"all {capped} healthy replicas are at their "
                        f"inflight cap ({self.inflight_cap})",
                        reason="overload")
                raise ServingError(
                    "no healthy replica remains to dispatch to",
                    reason="replica_down")
            candidates.sort(key=lambda c: c[:3])
            _infl, _q, _p99, rank, tel = candidates[0]
            self._inflight[rank] = self._inflight.get(rank, 0) + 1
            self._stats["routed"][rank] = \
                self._stats["routed"].get(rank, 0) + 1
            return {"rank": rank, "port": int(tel["port"]),
                    "seq": table[rank]["seq"]}

    # -- request path -------------------------------------------------------
    def infer(self, model: str, feeds: Dict[str, np.ndarray],
              deadline_ms: Optional[float] = None) -> List[np.ndarray]:
        """Route one inference to the least-loaded healthy replica."""
        with self._lock:
            self._stats["requests"] += 1
        _MON.counter("serving.fleet.requests").inc()
        msg = {"op": "infer", "model": model,
               "feeds": encode_feeds(feeds), "deadline_ms": deadline_ms}
        tried = 0
        world = getattr(self.health, "world", 1)
        while True:
            table = self.health.poll()
            try:
                pick = self._pick(table)
            except ServingError as e:
                self._account_error(e.reason)
                raise
            rank, port, seq = pick["rank"], pick["port"], pick["seq"]
            try:
                try:
                    reply = rpc(port, msg, timeout_s=self.rpc_timeout_s)
                except ConnectFailed as e:
                    # nothing was accepted: safe to retry elsewhere
                    self._mark_suspect(rank, seq)
                    tried += 1
                    if tried >= max(world, 1):
                        err = ServingError(
                            f"every replica refused the connection "
                            f"(last: rank {rank}: {e})",
                            reason="replica_down", model=model)
                        self._account_error("replica_down")
                        raise err from e
                    with self._lock:
                        self._stats["retries"] += 1
                    continue
                except OSError as e:
                    # the connection died with the request possibly
                    # executing: classified loss, never blind-retried
                    self._mark_suspect(rank, seq)
                    err = ServingError(
                        f"replica rank {rank} died with this request "
                        f"in flight: {e}",
                        reason="replica_down", model=model)
                    self._account_error("replica_down")
                    raise err from e
            finally:
                with self._lock:
                    n = self._inflight.get(rank, 1)
                    self._inflight[rank] = max(n - 1, 0)
            if reply.get("ok"):
                with self._lock:
                    self._stats["completed"] += 1
                _MON.counter("serving.fleet.completed").inc()
                return decode_arrays(reply["outputs"])
            reason = reply.get("reason") or "error"
            self._account_error(reason)
            raise ServingError(
                reply.get("error") or f"replica rank {rank} refused",
                reason=reason, model=model,
                trace_id=reply.get("trace_id"))

    def _account_error(self, reason: Optional[str]):
        reason = reason or "error"
        with self._lock:
            self._stats["errors"] += 1
            self._stats["by_reason"][reason] = \
                self._stats["by_reason"].get(reason, 0) + 1
        _MON.counter("serving.fleet.errors").inc()
        _MON.counter(f"serving.fleet.errors[{reason}]").inc()

    # -- introspection ------------------------------------------------------
    def inflight(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._inflight)

    def stats(self) -> dict:
        with self._lock:
            s = copy.deepcopy(self._stats)
        table = self.health.poll()
        s["replicas"] = {r: info["status"] for r, info in table.items()}
        s["healthy"] = sorted(r for r, info in table.items()
                              if info["status"] == "alive")
        s["ts"] = time.time()
        return s
