"""Verified hot model reload: publish / verify / swap / rollback.

The robustness centerpiece of the serving runtime.  A training gang
publishes into a live server by pointing `publish()` at either

  * a `CheckpointManager` COMMITTED checkpoint directory (or the manager
    itself — its `latest()` is used): a WEIGHTS-ONLY reload into the
    model's existing program, or
  * an inference-model directory (`io.save_inference_model` /
    `save_quantized_inference_model` output): a full program + weights
    replacement.

Nothing touches traffic until the staged snapshot survives the whole
verification ladder:

  1. commit integrity — a distributed checkpoint without its COMMITTED
     marker (or a `.tmp` pending dir) is torn by definition;
  2. content digests (ISSUE 14) — every manifest-stamped file re-hashes
     to its recorded sha256 + byte length BEFORE anything stages: a
     flipped-yet-finite byte quarantines in milliseconds, never paying
     the smoke/warm ladder to find out;
  3. manifest/shard integrity — the manifest must parse and every shard
     it names must load fully (a truncated .npy raises, never serves);
  4. program verification — `core/analysis.check_program` (structural)
     over the staged program with the model's feed/fetch targets;
  5. weight health — any non-finite value in a staged float weight
     rejects (a NaN weight WILL poison every request); SelectedRows
     vars take the SPARSE rung instead (ISSUE 19): row ids must be
     integral, strictly increasing, and inside [0, height), values must
     be finite, and the staged sparse content digest
     (`integrity.sparse_state_digest`) is stamped on the publish event
     for `serve_trace --fleet --check` to reconcile against what each
     replica loaded;
  6. golden-input smoke inference — the staged predictor must produce
     finite outputs on a golden batch (caller-provided, or synthesized
     from the program's feed specs), and match `golden_expect` when the
     caller pins one;
  7. quantized-snapshot accuracy parity (ISSUE 17) — a quant snapshot
     (`__quant__.json` present) publishing over a parent with the same
     feed/fetch contract must reproduce the ACTIVE version's outputs on
     the same feeds within `FLAGS_serving_quant_atol`; quantization
     drift past the gate is a content defect and rejects + quarantines
     exactly like a NaN weight;
  8. pre-swap compile lane — the serving buckets are warmed on the
     STAGED version, so the post-swap steady state never compiles
     inline.

Any CONTENT failure QUARANTINES the snapshot: the source dir lands in
the registry's quarantine set (repeat publishes reject fast), a
`serving.publish_rejected` event + counter record what and why, and a
classified `ServingError(reason="publish_rejected")` raises — while the
OLD version keeps serving untouched.  On success the swap is atomic
(registry lock), in-flight batches finish on the version they acquired,
and the previous version is retained for instant `rollback()`.

Transient STORE I/O is not a content failure (ISSUE 15): an EIO/timeout
while hashing or staging the snapshot says nothing about its bytes — a
flaky NFS read must never permanently poison a good snapshot.  Rungs
that touch the store (digest fast-reject, staging) classify their
failures through `errors.StorageError`: a transient one retries the
whole ladder with seeded backoff (`serving.publish_retries` counter,
`publish_io_retry` events), and exhausting the retries raises
`ServingError(reason="publish_io")` with NO quarantine — the next
publish attempt of the same source starts clean.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Sequence

import numpy as np

from .. import integrity as _integrity
from ..checkpoint_manager import COMMITTED_MARKER, DIST_MARKER, CheckpointManager
from ..core.analysis import check_program
from ..core.scope import Scope
from ..errors import ServingError, StorageError, classify
from ..flags import flag as _flag
from ..inference import Predictor
from ..monitor import MONITOR as _MON
from .. import io as _io
from . import tracing as _tr
from .registry import (ModelRegistry, ModelVersion, quant_manifest,
                       synthetic_feeds)

__all__ = ["publish", "rollback", "verify_snapshot_dir",
           "QUARANTINE_MARKER", "quarantine_marker"]

# Persisted quarantine (ISSUE 18): a content rejection also drops a
# marker file NEXT TO the snapshot (shared model store), so every OTHER
# replica of a serving fleet fast-rejects the same version without
# re-paying the stage/compile/smoke ladder N times — and without any
# channel beyond the store itself.  Written through the io.py atomic
# choke point; best-effort (a read-only store cannot take the marker,
# and the in-memory set still protects this process).
QUARANTINE_MARKER = "__quarantined__.json"

# transient-store-I/O retry budget per publish() call (the ladder is
# idempotent up to the swap, so re-running it whole is safe and keeps
# the rung code straight-line)
PUBLISH_IO_ATTEMPTS = 3


class _RetryableStoreIO(Exception):
    """Internal: a ladder rung hit transient store I/O — retry the
    ladder, do NOT quarantine."""


def _store_io_failure(e: BaseException) -> Optional[StorageError]:
    """The StorageError behind `e` (transient OR terminal), walking the
    cause chain (verify/stage helpers may wrap the raw OSError), else
    None.  Either flavor is a verdict about the STORE, not the snapshot
    — neither may quarantine."""
    seen = set()
    cur: Optional[BaseException] = e
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        ce = classify(cur)
        if isinstance(ce, StorageError):
            return ce
        cur = cur.__cause__ or cur.__context__
    return None


def _fail_publish_io(name: str, src: str, cause, attempts: int,
                     trace_id=None):
    """Classified store-I/O publish failure: loud, NO quarantine — the
    snapshot may be fine, the store is not."""
    _MON.counter("serving.publish_io_failed").inc()
    _MON.record_step({
        "kind": "serving_event", "action": "publish_io_failed",
        "model": name, "src": src, "attempts": attempts,
        "detail": str(cause), "trace_id": trace_id})
    raise ServingError(
        f"publish of {src!r} into model {name!r} failed on store I/O "
        f"after {attempts} attempt(s) ({cause}); NOT quarantined — the "
        f"snapshot may be fine, the store is not",
        reason="publish_io", model=name, trace_id=trace_id) from cause


def quarantine_marker(src: str) -> Optional[dict]:
    """The persisted quarantine verdict next to snapshot `src`, or None.
    Tolerates a torn/garbage marker (it still quarantines — the verdict
    is the file's existence; the payload is advisory detail)."""
    path = os.path.join(src, QUARANTINE_MARKER)
    if not os.path.exists(path):
        return None
    try:
        doc = _io.read_json(path)
        return doc if isinstance(doc, dict) else {}
    except Exception:
        return {}


def _write_quarantine_marker(src: str, name: str, detail: str, trace_id):
    """Best-effort persisted verdict (see QUARANTINE_MARKER).  Exempt
    from INJECTED io faults: the marker is the fleet-wide record OF a
    content rejection — a chaos spec aimed at the snapshot's data path
    must not eat the verdict it just provoked.  Real OSErrors (read-only
    store, full disk) are counted, not fatal: the in-memory set still
    protects this process."""
    doc = {"model": name, "detail": detail, "trace_id": trace_id,
           "ts": time.time(), "pid": os.getpid(),
           "rank": os.environ.get("PADDLE_TRAINER_ID")}
    try:
        with _io.fault_exempt(src):
            _io.atomic_write(os.path.join(src, QUARANTINE_MARKER),
                             json.dumps(doc, default=str))
    except OSError:
        _MON.counter("serving.quarantine_marker_errors").inc()


def _reject(registry: ModelRegistry, name: str, src: str, trace_id,
            detail: str, marker: bool = True):
    registry.quarantined.add(os.path.realpath(src))
    if marker and os.path.isdir(src):
        _write_quarantine_marker(src, name, detail, trace_id)
    _MON.counter("serving.publish_rejected").inc()
    _MON.record_step({"kind": "serving_event", "action": "publish_rejected",
                      "model": name, "src": src, "detail": detail,
                      "trace_id": trace_id})
    # a rejected publish is exactly the kind of episode a post-mortem
    # starts from: retain it in the black box's exemplar ring (ISSUE 16)
    _MON.record_exemplar({"kind": "serving_trace", "trace_id": trace_id,
                          "model": name, "outcome": "error",
                          "reason": "publish_rejected", "src": src,
                          "detail": detail})
    raise ServingError(
        f"publish of {src!r} into model {name!r} REJECTED and quarantined "
        f"({detail}); the previous version keeps serving",
        reason="publish_rejected", model=name, trace_id=trace_id)


def verify_snapshot_dir(src: str) -> str:
    """Static integrity checks every publish source must pass; returns
    the snapshot kind ('inference' | 'checkpoint' | 'vars').  Raises
    ValueError naming the defect — publish() maps that to a classified
    rejection."""
    if not os.path.isdir(src):
        raise ValueError(f"{src!r} is not a directory")
    if src.rstrip(os.sep).endswith(".tmp"):
        raise ValueError("pending (.tmp) checkpoint dir — not committed")
    # a distributed checkpoint must carry rank 0's COMMITTED marker; its
    # absence means some rank's shards never arrived (torn commit)
    if (os.path.exists(os.path.join(src, DIST_MARKER))
            and not os.path.exists(os.path.join(src, COMMITTED_MARKER))):
        raise ValueError("distributed checkpoint without COMMITTED marker "
                         "(torn commit)")
    if os.path.exists(os.path.join(src, _io.MODEL_FILENAME)):
        return "inference"
    if os.path.exists(os.path.join(src, _io.SHARDED_MANIFEST)):
        return "checkpoint"
    if os.path.exists(os.path.join(src, _io.MANIFEST)):
        return "vars"
    raise ValueError("no __model__.json, sharded manifest, or manifest — "
                     "not a model or checkpoint directory")


def _stage(registry: ModelRegistry, current: ModelVersion, src: str,
           kind: str):
    """Load the snapshot into a fresh staged scope; returns (program,
    feed_names, fetch_names, scope).  Any load failure (truncated shard,
    bad manifest JSON, missing param) raises — callers reject."""
    staged = Scope()
    # verify=False: the digest fast-reject rung just re-hashed every
    # manifest-stamped file in `src` — hashing a multi-GB snapshot twice
    # per publish would double the I/O cost of the ladder for nothing
    if kind == "inference":
        program, feed_names, fetch_names = _io.load_inference_model(
            src, registry.executor, scope=staged, verify=False)
        return program, feed_names, fetch_names, staged
    # weights-only reload: the program (and its feed/fetch contract) come
    # from the version currently serving
    params = [v.name for v in _io._persistables(current.program)]
    if kind == "checkpoint":
        _io.load_sharded(src, var_names=params, scope=staged, verify=False)
    else:
        _io.load_vars(src, var_names=params, scope=staged, verify=False)
    return (current.program, current.feed_names, current.fetch_names, staged)


def publish(registry: ModelRegistry, name: str, src,
            golden_feeds: Optional[Dict[str, np.ndarray]] = None,
            golden_expect: Optional[Sequence[np.ndarray]] = None,
            golden_rtol: float = 1e-4, golden_atol: float = 1e-5,
            warm_buckets: Optional[Sequence[int]] = None,
            stage_only: bool = False) -> ModelVersion:
    """Verify `src` and atomically swap it in as model `name`'s served
    version (old version retained for rollback()).  See the module
    docstring for the verification ladder; every failure raises a
    classified ServingError(reason="publish_rejected") with the old
    version still serving.

    `stage_only=True` runs the ENTIRE ladder (verification rungs AND the
    pre-swap bucket warm) but holds the verified version in the
    registry's staged slot instead of swapping — phase one of the
    fleet's two-phase rolling publish (serving/fleet.py); activate with
    `registry.activate_staged(name)` once every replica has acked."""
    if isinstance(src, CheckpointManager):
        latest = src.latest()
        if latest is None:
            # no marker: "nothing committed YET" is a verdict about the
            # manager's state, not about any snapshot's content
            _reject(registry, name, src.root,
                    "CheckpointManager has no committed checkpoint",
                    marker=False)
        src = latest
    src = str(src)
    # One publish ladder at a time per model: a concurrent publish into
    # the same model would double-stage/double-warm and could retain the
    # LOSER's fresh version as the "previous" rollback target instead of
    # the version traffic was actually on.  Serialization is an in-flight
    # marker, not a lock held across the ladder — staging and the
    # pre-swap warm block on disk/XLA for seconds, and no lock (so no
    # other thread, not even another model's publish) waits that out.
    with registry._publish_cv:
        while name in registry._publishing:
            registry._publish_cv.wait(0.1)
        registry._publishing.add(name)
    # one control trace id per publish EPISODE (retries included), so
    # every event/rejection/retry of this reload is addressable on the
    # same timeline as the requests it raced (serving/tracing.py)
    ctl = _tr.control_trace_id("pub")
    try:
        # transient store I/O retries the whole ladder (idempotent up to
        # the swap); content defects quarantine inside the ladder as ever
        attempt = 0
        while True:
            try:
                return _publish_ladder(registry, name, src, golden_feeds,
                                       golden_expect, golden_rtol,
                                       golden_atol, warm_buckets, ctl,
                                       stage_only=stage_only)
            except _RetryableStoreIO as e:
                cause = e.__cause__
                attempt += 1
                if attempt >= PUBLISH_IO_ATTEMPTS:
                    _fail_publish_io(name, src, cause, attempt,
                                     trace_id=ctl)
                _MON.counter("serving.publish_retries").inc()
                _MON.record_step({
                    "kind": "serving_event", "action": "publish_io_retry",
                    "model": name, "src": src, "attempt": attempt,
                    "detail": str(cause), "trace_id": ctl})
                from ..resilience import RetryPolicy

                time.sleep(RetryPolicy().backoff_s(attempt - 1))
    finally:
        with registry._publish_cv:
            registry._publishing.discard(name)
            registry._publish_cv.notify_all()


def _publish_ladder(registry, name, src, golden_feeds, golden_expect,
                    golden_rtol, golden_atol, warm_buckets, ctl=None,
                    stage_only=False):
    with _MON.span("serving.publish", model=name, trace_id=ctl):
        # publish reloads an EXISTING model (use registry.load for new
        # names); a missing target is the caller's error, not the
        # snapshot's, so it raises model_missing rather than quarantining
        active = registry.acquire(name)
        if os.path.realpath(src) in registry.quarantined:
            _reject(registry, name, src, ctl,
                    "source already quarantined by an earlier rejected "
                    "publish")
        # fleet-wide fast-reject (ISSUE 18): a marker persisted next to
        # the snapshot by ANY replica's rejection spares this one the
        # whole stage/compile/smoke ladder
        mk = quarantine_marker(src)
        if mk is not None:
            registry.quarantined.add(os.path.realpath(src))
            who = mk.get("rank")
            _reject(registry, name, src, ctl,
                    f"source carries a persisted quarantine marker"
                    f"{f' (rejected by replica {who})' if who is not None else ''}"
                    f": {mk.get('detail', 'no detail recorded')}",
                    marker=False)
        try:
            kind = verify_snapshot_dir(src)
        except ValueError as e:
            _reject(registry, name, src, ctl, f"integrity: {e}")
        # digest fast-reject (ISSUE 14): re-hash every manifest-stamped
        # file BEFORE staging — a rotted snapshot quarantines in
        # milliseconds instead of paying the stage/verify/smoke/warm
        # ladder to discover the same thing (and a rot the load path
        # happens not to materialize, e.g. an unreferenced shard, still
        # rejects)
        try:
            with _MON.span("serving.publish_digest_check", model=name):
                _integrity.verify_manifest_digests(src)
        except Exception as e:
            se = _store_io_failure(e)
            if se is not None and se.transient:
                raise _RetryableStoreIO(str(e)) from e
            if se is not None:
                # terminal store I/O (EACCES/EROFS): retrying is useless,
                # but quarantining would record a content verdict no
                # content check made — classified failure, clean slate
                _fail_publish_io(name, src, se, attempts=1, trace_id=ctl)
            _reject(registry, name, src, ctl,
                    f"integrity: manifest digest check failed ({e})")
        try:
            program, feed_names, fetch_names, staged = _stage(
                registry, active, src, kind)
        except Exception as e:
            se = _store_io_failure(e)
            if se is not None and se.transient:
                raise _RetryableStoreIO(str(e)) from e
            if se is not None:
                _fail_publish_io(name, src, se, attempts=1, trace_id=ctl)
            _reject(registry, name, src, ctl,
                    f"staging failed ({type(e).__name__}: {e})")
        # program verification (core/analysis): the staged program must
        # pass the structural verifier with the serving feed/fetch targets
        try:
            check_program(program, level="structural",
                          feed_names=feed_names, fetch_names=fetch_names)
        except Exception as e:
            _reject(registry, name, src, ctl, f"program verification: {e}")
        # weight health: a non-finite weight poisons every request.
        # SelectedRows vars take the SPARSE rung instead (ISSUE 19):
        # structural validation (row-id monotonicity + range, shape
        # agreement) plus the non-finite scan, and their content digest
        # is stamped on the publish event so serve_trace --fleet --check
        # can reconcile what was published against what every replica
        # actually loaded (a torn publish shows up as disagreement)
        from ..core.selected_rows import SelectedRows as _SR

        for vname in staged.local_var_names():
            v = staged.find_var(vname)
            if isinstance(v, _SR):
                defect = _integrity.check_selected_rows(vname, v)
                if defect is not None:
                    _reject(registry, name, src, ctl,
                            f"sparse table rung: {defect}")
                continue
            arr = np.asarray(v)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                _reject(registry, name, src, ctl,
                        f"non-finite values in staged weight {vname!r}")
        sparse_digest = _integrity.sparse_state_digest(staged)
        # golden-input smoke on the staged predictor (shared executor:
        # the smoke run is also the bucket-1-shaped compile)
        predictor = Predictor(active.predictor.config,
                              _shared=(program, feed_names,
                                       fetch_names, staged),
                              executor=registry.executor)
        feeds = golden_feeds
        if feeds is None:
            feeds = synthetic_feeds(program, feed_names, rows=1)
        try:
            outs = predictor.run(feeds)
        except Exception as e:
            _reject(registry, name, src, ctl,
                    f"golden smoke inference failed "
                    f"({type(e).__name__}: {e})")
        for fname, o in zip(fetch_names, outs):
            arr = np.asarray(o)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                _reject(registry, name, src, ctl,
                        f"golden smoke produced non-finite {fname!r}")
        if golden_expect is not None:
            if len(golden_expect) != len(fetch_names):
                # zip() would silently stop comparing at the shorter list,
                # leaving trailing fetches unverified — that is a caller
                # bug the ladder must not paper over
                _reject(registry, name, src, ctl,
                        f"golden_expect carries {len(golden_expect)} "
                        f"entries but the model fetches "
                        f"{len(fetch_names)} ({fetch_names})")
            for fname, got, want in zip(fetch_names, outs, golden_expect):
                if not np.allclose(np.asarray(got), np.asarray(want),
                                   rtol=golden_rtol, atol=golden_atol):
                    _reject(registry, name, src, ctl,
                            f"golden output {fname!r} drifted past "
                            f"rtol={golden_rtol}")
        # quantized-snapshot accuracy parity: a quant dir publishing
        # over a parent with the same feed/fetch contract must agree
        # with the ACTIVE version's outputs on the same feeds within
        # FLAGS_serving_quant_atol — quantization drift past the gate
        # is a content defect, same rejection path as a NaN weight
        if (quant_manifest(src) is not None
                and active.feed_names == list(feed_names)
                and active.fetch_names == list(fetch_names)):
            atol = float(_flag("FLAGS_serving_quant_atol") or 0.0)
            try:
                ref = active.run(feeds)
            except Exception:
                # the parent cannot run these feeds (e.g. it is itself
                # mid-replacement); nothing sound to gate against
                ref = None
            if ref is not None and atol > 0:
                worst, worst_name = 0.0, None
                for fname, got, want in zip(fetch_names, outs, ref):
                    g = np.asarray(got, np.float64)
                    w = np.asarray(want, np.float64)
                    if g.shape != w.shape:
                        _reject(registry, name, src, ctl,
                                f"quant parity: output {fname!r} shape "
                                f"{g.shape} != serving parent's {w.shape}")
                    d = float(np.max(np.abs(g - w))) if g.size else 0.0
                    if d > worst:
                        worst, worst_name = d, fname
                if worst > atol:
                    _reject(registry, name, src, ctl,
                            f"quant parity: output {worst_name!r} drifted "
                            f"max|diff|={worst:.3e} past "
                            f"FLAGS_serving_quant_atol={atol:g} vs the "
                            f"serving parent's outputs")
                _MON.record_step({
                    "kind": "serving_event", "action": "quant_parity",
                    "model": name, "src": src, "max_abs_diff": worst,
                    "atol": atol, "trace_id": ctl})
        version = ModelVersion(program, feed_names, fetch_names, staged,
                               predictor, src=src)
        # pre-swap compile lane: warm the serving buckets on the STAGED
        # version so post-swap traffic never waits on XLA.  A model that
        # cannot compile its buckets is not servable — same rejection
        # path as every other rung (quarantine + event + classified)
        try:
            for b in sorted(set(int(b) for b in (warm_buckets or ()))):
                with _MON.span("serving.warm", model=name, bucket=b):
                    predictor.run(synthetic_feeds(program, feed_names, b))
        except Exception as e:
            _reject(registry, name, src, ctl,
                    f"pre-swap bucket warm failed "
                    f"({type(e).__name__}: {e})")
        if stage_only:
            # two-phase fleet roll: the version is verified and warm but
            # traffic stays on the old one until activate_staged
            registry.stage_version(name, version)
            _MON.record_step({"kind": "serving_event",
                              "action": "publish_staged", "model": name,
                              "src": src, "version": version.version,
                              "precision": version.precision,
                              "sparse_digest": sparse_digest,
                              "trace_id": ctl})
            return version
        prev = registry.publish_version(name, version)
        _MON.counter("serving.reloads").inc()
        _MON.record_step({"kind": "serving_event", "action": "publish",
                          "model": name, "src": src,
                          "version": version.version,
                          "prev_version": prev.version,
                          "precision": version.precision,
                          "sparse_digest": sparse_digest,
                          "trace_id": ctl})
    return version


def rollback(registry: ModelRegistry, name: str) -> ModelVersion:
    """Instantly re-activate the retained previous version."""
    return registry.rollback(name)
