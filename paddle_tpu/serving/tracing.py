"""Per-request flight tracing for the serving runtime (ISSUE 16).

Every `Server.submit` acquires a `RequestTrace`: a trace id plus a span
tree over the request's whole flight —

    admission -> queue -> batch_build -> device -> fetch -> respond

(`batch_build` carries the pad attribution: which bucket the batch
padded to and how many pad rows rode along; `device` is the blocking
predictor call, which on the synchronous CPU/TPU predictor path folds
XLA dispatch + execute + array fetch into one phase — `fetch` is the
host-side result splitting).  A request that never completes still gets
a CLOSED trace: shed, timeout, error, shutdown, and door rejections each
close the trace with the same stable reason code the raised
`ServingError` carries, so `requests == completed + shed + timeouts +
errors + shutdowns` reconciles in the trace stream exactly as it does in
the server ledger (`tools/serve_trace.py --check` gates it).

Hot-path contract (the PR-8 flight-recorder discipline): with the
monitor DISABLED `maybe_trace` is one attribute load + branch returning
the shared `NULL_TRACE` singleton, and every phase/annotate/close on it
is a no-op — tests/test_request_tracing.py pins the µs-scale bound.
Enabled, a trace is a handful of `perf_counter` marks and ONE
`Monitor.record_trace` at close (bounded ring + `serving_trace` step
record; see monitor/core.py).

Control-plane actions (publish, rollback) get their own ids via
`control_trace_id` so a reload episode is addressable on the same
timeline as the requests it raced.
"""
from __future__ import annotations

import itertools
import time
from typing import Optional

__all__ = ["RequestTrace", "NULL_TRACE", "maybe_trace", "control_trace_id",
           "TRACE_PHASES"]

# canonical phase order of a completed request's span tree
TRACE_PHASES = ("admission", "queue", "batch_build", "device", "fetch",
                "respond")

# terminal outcomes a closed trace may carry; "rejected" covers the
# admission-door refusals (bad_request/oversize/model_missing) that never
# enter the server's `requests` ledger
TERMINAL_OUTCOMES = ("completed", "shed", "timeout", "error", "shutdown",
                     "rejected")

_ids = itertools.count(1)          # next() is atomic under the GIL
_ctl_ids = itertools.count(1)


def control_trace_id(prefix: str) -> str:
    """Trace id for a control-plane action (publish/rollback) so reload
    episodes are addressable in `serve_trace --request` next to the
    requests they raced."""
    return f"{prefix}-{next(_ctl_ids):04d}"


class _NullTrace:
    """Shared do-nothing trace returned while the monitor is disabled —
    the disabled serving hot path must not allocate per request."""

    __slots__ = ()
    enabled = False
    trace_id = None

    def phase(self, name, t=None):
        return self

    def annotate(self, **kw):
        return self

    def close(self, outcome, reason=None, final=None, **annot):
        return None


NULL_TRACE = _NullTrace()


def maybe_trace(mon, model: str, rows=None,
                deadline_ms: Optional[float] = None):
    """The submit-door entry point: `NULL_TRACE` (no allocation) when the
    monitor is disabled, a live `RequestTrace` when it is on."""
    if not mon.enabled:
        return NULL_TRACE
    return RequestTrace(model, rows=rows, deadline_ms=deadline_ms)


class RequestTrace:
    """One request's span tree, built from phase BOUNDARIES: the trace
    opens at submit (wall `ts` + perf_counter `t0`); each `phase(name)`
    closes the currently-open phase under that name; `close(outcome)`
    seals the final phase and renders the record.  First close wins —
    the worker-loop catch-all may try to error-close a request a
    deadline already cancelled."""

    __slots__ = ("trace_id", "model", "rows", "deadline_ms", "ts", "t0",
                 "marks", "args", "outcome", "reason")

    enabled = True

    def __init__(self, model: str, rows=None,
                 deadline_ms: Optional[float] = None):
        self.trace_id = f"r{next(_ids):06d}"
        self.model = model
        self.rows = rows
        self.deadline_ms = deadline_ms
        self.ts = time.time()
        self.t0 = time.perf_counter()
        self.marks = []          # [(phase_name, perf_counter_at_end), ...]
        self.args = {}
        self.outcome = None      # set exactly once, by close()
        self.reason = None

    def phase(self, name: str, t: Optional[float] = None):
        """Close the currently-open phase as `name` (ended now, or at the
        shared timestamp `t` a batch-level boundary passes to every
        member request)."""
        if self.outcome is None:
            self.marks.append((name, time.perf_counter() if t is None
                               else t))
        return self

    def annotate(self, **kw):
        self.args.update(kw)
        return self

    def close(self, outcome: str, reason: Optional[str] = None,
              final: Optional[str] = None, **annot):
        """Seal the trace: record the final phase (`final`, default
        "respond"), stamp outcome + stable reason code, and return the
        JSON-able `serving_trace` record (None on a repeat close)."""
        if self.outcome is not None:
            return None
        self.marks.append((final or "respond", time.perf_counter()))
        self.outcome = outcome
        self.reason = reason
        if annot:
            self.args.update(annot)
        return self._record()

    def _record(self) -> dict:
        spans, prev = [], self.t0
        for name, t in self.marks:
            spans.append({"name": name,
                          "t_ms": round((prev - self.t0) * 1e3, 4),
                          "dur_ms": round(max(t - prev, 0.0) * 1e3, 4)})
            prev = t
        total_ms = round((prev - self.t0) * 1e3, 4)
        rec = {"kind": "serving_trace", "trace_id": self.trace_id,
               "model": self.model, "rows": self.rows,
               "outcome": self.outcome, "ts": self.ts,
               "total_ms": total_ms, "spans": spans}
        if self.reason is not None:
            rec["reason"] = self.reason
        if self.deadline_ms:
            rec["deadline_ms"] = self.deadline_ms
        rec.update(self.args)
        return rec
