"""Chaos campaign engine (ISSUE 20): seeded multi-fault schedules,
a cross-subsystem invariant registry, and automatic failure-spec
shrinking — the generative layer over paddle_tpu/faults.py.

Every fault kind the platform survives is pinned by a hand-written
single-fault test somewhere; real outages are correlated COMPOUNDS (a
pserver SIGKILL during a rolling publish while the checkpoint store
throws ENOSPC).  This module turns the fault matrix into a Jepsen-style
instrument:

  * `generate_schedule` draws a seeded pseudo-random multi-fault
    schedule — weighted draws over the `KIND_INFO` kinds whose `needs`
    the chosen scenario provides, plus deliberately adversarial pairing
    templates (a storage fault inside a preemption-resume window, an
    ENOSPC landing exactly on a publish-cadence step, a rotted snapshot
    plus a flaky read in one publish) — rendered as a plain
    `FLAGS_fault_spec` string, so every campaign run is replayable by
    copy-paste through the ordinary single-run path.
  * `run_one(scenario, spec, seed)` IS that ordinary single-run path:
    the campaign, the shrinker, the `--replay` CLI, and a human pasting
    a spec all route through it, which is what makes the replay-verdict
    determinism contract (same scenario+spec+seed -> same invariant
    verdict) hold by construction.
  * `evaluate` runs the declarative `INVARIANTS` registry over the
    run's probes: exact serving-ledger identity, zero dropped /
    double-trained samples, bit-identical recovery against an
    uninterrupted arm, publish-cadence bound, no quarantined-good-
    snapshot, monitor counters reconciled against injector fire counts.
    Each violation is classified (ledger / recovery / cadence /
    quarantine / accounting / crash).
  * `shrink` reduces a failing schedule by greedy fault-removal then
    step-bisection to a minimal still-failing `FLAGS_fault_spec`;
    `run_campaign` writes each failure as a `CHAOS_REPRO.json` naming
    the schedule, seed, violated invariant, and shrunk spec.

Scenarios are deliberately tiny (CPU, a 4-wide net, ~10 steps) so a
tier-1 smoke (`tools/chaos_campaign.py --check --smoke`) fits the
budget.  The planted-defect proof: `PADDLE_CHAOS_PLANTED_BUG=1`
re-enables a (simulated) stale-restore race in the train scenario that
only a nan+device compound exposes — the smoke asserts a seeded
campaign catches it and the shrinker converges to a <=2-fault spec
that still fails.

Campaign metrics ride the monitor: `chaos_event` step records plus
`chaos.schedules_run` / `chaos.invariants_checked` /
`chaos.invariant_violations` counters, gated by
`perf_report --check --max-chaos-violations` (zero evidence fails).
"""
from __future__ import annotations

__all__ = ["RunResult", "Violation", "ShrinkResult", "CampaignResult",
           "SCENARIOS", "INVARIANTS", "PLANTED_BUG_ENV",
           "generate_schedule", "run_one", "evaluate", "invariants_for",
           "shrink", "run_campaign"]

import json
import os
import random
import tempfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .faults import (KIND_INFO, FaultInjector, parse_fault_spec,
                     sweep_stale_ledgers, validate_schedule)
from .monitor import MONITOR as _MON

# the (simulated) planted defect: with this env var set, the train
# scenario perturbs post-recovery state whenever BOTH a nan and a
# device fault fired in one run — the re-enabled stale-restore race
# class only a compound exposes.  bit_identical_recovery catches it;
# greedy removal can drop NEITHER fault (either alone passes), so the
# shrinker provably converges to an exactly-2-fault spec.
PLANTED_BUG_ENV = "PADDLE_CHAOS_PLANTED_BUG"

_HORIZON = 10          # train/online steps per scenario run
_PUBLISH_PERIOD = 3
_D_IN = 4


# --------------------------------------------------------------------------
# run / verdict plumbing
# --------------------------------------------------------------------------

@dataclass
class RunResult:
    """One schedule's run through the ordinary single-run path."""
    scenario: str
    spec: str
    seed: int
    ok: bool                      # completed without an unhandled crash
    error: Optional[str] = None
    fired: Dict[str, int] = field(default_factory=dict)   # incl. replays
    data: Dict[str, Any] = field(default_factory=dict)    # invariant probes
    counters: Dict[str, int] = field(default_factory=dict)  # monitor deltas


@dataclass
class Violation:
    invariant: str
    cls: str          # ledger | recovery | cadence | quarantine | accounting | crash
    message: str


@dataclass
class ShrinkResult:
    spec: str         # minimal still-failing FLAGS_fault_spec
    runs: int         # probe runs spent
    converged: bool   # every removal/bisection candidate was re-verified


@dataclass
class CampaignResult:
    schedules_run: int = 0
    invariants_checked: int = 0
    violations: List[dict] = field(default_factory=list)
    schedules: List[dict] = field(default_factory=list)
    repro_paths: List[str] = field(default_factory=list)
    out_dir: str = ""
    metrics_path: Optional[str] = None


@dataclass
class Scenario:
    name: str
    capabilities: Tuple[str, ...]
    kinds: Tuple[str, ...]
    runner: Callable[[str, int, str], Tuple[Dict[str, Any], Dict[str, int]]]
    templates: Tuple[Callable[[random.Random], str], ...] = ()
    smoke: bool = True     # included in the tier-1 --smoke set


# --------------------------------------------------------------------------
# tiny deterministic workloads (shared across scenarios)
# --------------------------------------------------------------------------

def _tiny_net(seed: int = 11):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [_D_IN], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 6, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    startup.random_seed = seed
    main.random_seed = seed
    return main, startup, loss


def _tiny_feeds(n: int, batch: int = 4):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(n):
        xv = rng.rand(batch, _D_IN).astype("f4")
        out.append({"x": xv, "y": xv.sum(1, keepdims=True)})
    return out


def _params(scope) -> Dict[str, np.ndarray]:
    out = {}
    for name in sorted(scope.local_var_names()):
        try:
            out[name] = np.asarray(scope.find_var(name)).copy()
        except Exception:
            continue
    return out


def _merge_fired(total: Dict[str, int], unique: set, inj) -> None:
    for f in inj.fired():
        total[f.kind] = total.get(f.kind, 0) + 1
        unique.add((f.kind, f.at))


# --------------------------------------------------------------------------
# scenario: resilient train loop
# --------------------------------------------------------------------------

def _run_train(spec: str, seed: int, workdir: str):
    import paddle_tpu as fluid
    from .checkpoint_manager import CheckpointManager

    main, startup, loss = _tiny_net()
    feeds = _tiny_feeds(_HORIZON)
    policy = fluid.RetryPolicy(max_bad_batches=6, max_skipped_steps=6,
                               max_device_retries=8, max_rollbacks=4,
                               backoff_base_s=0.0)
    flist = parse_fault_spec(spec)
    # the uninterrupted reference arm drops exactly the batches the data
    # faults drop (bad_batch / nan shape WHICH samples train; recovery
    # faults must be transparent) — so parity after device retries,
    # preemption resume, and storage windows is exact, not approximate
    data_only = ";".join(str(f) for f in flist
                         if f.kind in ("bad_batch", "nan"))

    def one_arm(tag: str, arm_spec: str, follow_preempt: bool):
        fired_total: Dict[str, int] = {}
        fired_unique: set = set()
        root = os.path.join(workdir, tag)
        resume = False
        segments = 0
        while True:
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            exe.run(startup, scope=scope)
            cm = CheckpointManager(root, program=main, scope=scope,
                                   save_every_steps=3)
            inj = FaultInjector(arm_spec, seed=seed)
            stats = fluid.resilient_train_loop(
                exe, main, lambda: list(feeds), [loss], scope=scope,
                injector=inj, nan_mode="skip_step", policy=policy,
                checkpoint_manager=cm, max_inflight=2, resume=resume)
            segments += 1
            _merge_fired(fired_total, fired_unique, inj)
            if not (stats.preempted and follow_preempt and segments < 4):
                return stats, scope, fired_total, fired_unique, segments
            # "fresh process" resume: pending entries carry over, plus
            # fired DATA faults — a bad record is a property of the
            # stream (still bad if the replay window re-pulls it), while
            # a fired preemption/device blip/storage window is an event
            # in time and must not repeat
            carry = inj.pending() + [f for f in inj.fired()
                                     if f.kind in ("bad_batch", "nan")]
            for f in carry:
                f.fired = False
            arm_spec = ";".join(str(f) for f in carry)
            resume = True

    # reference arm: monitor muted so campaign counter deltas reconcile
    # against the FAULTED arm's fires alone
    was = _MON.enabled
    _MON.disable()
    try:
        ref_stats, ref_scope, _, ref_unique, _ = one_arm(
            "ref", data_only, follow_preempt=False)
    finally:
        if was:
            _MON.enable()
    ref = _params(ref_scope)

    stats, scope, fired_total, fired_unique, segments = one_arm(
        "chaos", spec, follow_preempt=True)

    if os.environ.get(PLANTED_BUG_ENV) \
            and fired_total.get("nan") and fired_total.get("device"):
        # the planted stale-restore race: recovery state perturbed only
        # when the nan skip and a device retry compounded in one life
        for name, arr in _params(scope).items():
            if arr.dtype.kind == "f" and arr.size:
                arr = arr.copy()
                arr.flat[0] += 1e-3
                scope.set_var(name, arr)
                break

    got = _params(scope)
    diverged = sorted(
        n for n in ref
        if n not in got or not np.array_equal(ref[n], got[n]))
    dropped = len({(k, a) for (k, a) in fired_unique
                   if k in ("bad_batch", "nan")})
    data = {
        "n_feeds": len(feeds),
        "steps": stats.steps,
        "segments": segments,
        "dropped_unique": dropped,
        "preempted_final": stats.preempted,
        "diverged_vars": diverged,
        "ref_steps": ref_stats.steps,
    }
    return data, fired_total


# --------------------------------------------------------------------------
# scenario: online-learning publish cadence
# --------------------------------------------------------------------------

def _run_online(spec: str, seed: int, workdir: str):
    import paddle_tpu as fluid
    from . import io as _io

    main, startup, loss = _tiny_net()
    feeds = _tiny_feeds(_HORIZON)
    policy = fluid.RetryPolicy(max_bad_batches=6, max_skipped_steps=6,
                               max_device_retries=8, backoff_base_s=0.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    pname = next(v.name for v in main.list_vars() if v.persistable)
    pubs: List[int] = []

    def hook(step: int):
        # through the io.py choke point: enospc/eio windows fail this
        # write exactly like a full disk / flaky read would
        _io.save_vars(os.path.join(workdir, f"pub-{step}"), [pname], scope)
        pubs.append(step)

    inj = FaultInjector(spec, seed=seed)
    stats = fluid.resilient_train_loop(
        exe, main, lambda: list(feeds), [loss], scope=scope,
        injector=inj, nan_mode="skip_step", policy=policy,
        publish_hook=hook, publish_period_steps=_PUBLISH_PERIOD,
        max_inflight=2)
    fired_total: Dict[str, int] = {}
    fired_unique: set = set()
    _merge_fired(fired_total, fired_unique, inj)
    dropped = len({(k, a) for (k, a) in fired_unique
                   if k in ("bad_batch", "nan")})
    data = {
        "n_feeds": len(feeds),
        "steps": stats.steps,
        "segments": 1,
        "dropped_unique": dropped,
        "publishes": stats.publishes,
        "publish_failures": stats.publish_failures,
        "published_at": pubs,
        "period": _PUBLISH_PERIOD,
        "staleness": _MON.gauge_values().get(
            "serving.publish_staleness_steps"),
    }
    return data, fired_total


# --------------------------------------------------------------------------
# scenario: serving publish under traffic
# --------------------------------------------------------------------------

def _save_tiny_model(dirname: str, w_scale: float):
    import paddle_tpu as fluid
    from .core import unique_name

    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [_D_IN], dtype="float32")
            out = fluid.layers.fc(x, 2, act=None)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    startup.random_seed = 3
    exe.run(startup, scope=scope)
    for v in main.list_vars():
        if v.persistable:
            shape = np.asarray(scope.find_var(v.name)).shape
            scope.set_var(v.name, np.full(shape, w_scale, dtype="float32"))
    fluid.io.save_inference_model(dirname, ["x"], [out], exe, main, scope)
    return dirname


def _run_serving(spec: str, seed: int, workdir: str):
    import paddle_tpu as fluid
    from . import serving
    from .serving import quarantine_marker

    d1 = _save_tiny_model(os.path.join(workdir, "v1"), w_scale=1.0)
    d2 = _save_tiny_model(os.path.join(workdir, "v2"), w_scale=2.0)
    inj = FaultInjector(spec, seed=seed)
    reg = serving.ModelRegistry(place=fluid.CPUPlace())
    srv = serving.Server(reg, buckets=(2, 4))
    xv = np.full((2, _D_IN), 0.5, "f4")
    publish_ok = True
    try:
        srv.load_model("m", d1)
        for _ in range(3):
            srv.infer("m", {"x": xv})
        # the publish window: the v2 "commit" is the rot_shard target,
        # and the publish's store I/O rides the armed choke point
        inj.on_commit(d2)
        inj.arm_io()
        try:
            srv.publish("m", d2)
        except Exception:
            publish_ok = False
        finally:
            inj.disarm_io()
        futs = [srv.submit("m", {"x": xv}) for _ in range(4)]
        outs = []
        for f in futs:
            try:
                outs.append(np.asarray(f.result(timeout=30)[0]))
            except Exception:
                outs.append(None)
    finally:
        srv.stop()
    ledger = srv.ledger()
    fired_total: Dict[str, int] = {}
    _merge_fired(fired_total, set(), inj)
    # served function is x @ (s*1) + s  ->  s * (sum(x) + 1) per row
    scale = 2.0 if publish_ok else 1.0
    want = scale * (xv.sum(axis=1, keepdims=True) + 1.0)
    served_ok = all(o is not None and np.allclose(o, want) for o in outs)
    data = {
        "ledger": ledger,
        "publish_ok": publish_ok,
        "rot_fired": fired_total.get("rot_shard", 0),
        "quarantined": quarantine_marker(d2) is not None,
        "served_scale_ok": served_ok,
    }
    return data, fired_total


# --------------------------------------------------------------------------
# scenario: elastic gang (CLI-only: two real process gangs per run)
# --------------------------------------------------------------------------

def _gang_results(res) -> Dict[int, dict]:
    out = {}
    for rank, (_code, o, _e) in enumerate(res.workers):
        for line in (o or "").splitlines():
            if line.startswith("RESULT "):
                out[rank] = json.loads(line[len("RESULT "):])
    return out


def _run_gang(spec: str, seed: int, workdir: str):
    import sys

    from . import launch

    worker = os.environ.get("PADDLE_CHAOS_GANG_WORKER")
    if not worker or not os.path.exists(worker):
        raise RuntimeError(
            "gang scenario needs PADDLE_CHAOS_GANG_WORKER=<worker script> "
            "(e.g. tests/dist_worker_resilient.py); it is excluded from "
            "--smoke for exactly this reason")
    env = {"RUN_STEPS": "8", "SAVE_EVERY": "2",
           "FLAGS_dist_heartbeat_interval_s": "0.25",
           "FLAGS_dist_heartbeat_miss_factor": "12",
           "FLAGS_dist_watchdog_timeout_s": "60",
           "FLAGS_dist_bootstrap_timeout_s": "120"}
    ref = launch.run_gang([sys.executable, worker], 2,
                          checkpoint_root=os.path.join(workdir, "ref"),
                          extra_env=dict(env), max_restarts=1, timeout=240)
    cenv = dict(env)
    cenv["FLAGS_fault_spec"] = spec
    res = launch.run_gang([sys.executable, worker], 2,
                          checkpoint_root=os.path.join(workdir, "chaos"),
                          extra_env=cenv, max_restarts=3, timeout=240)
    ref_out, out = _gang_results(ref), _gang_results(res)
    data = {
        "ref_ok": ref.ok, "ok": res.ok, "restarts": res.restarts,
        "ref_sha": ref_out.get(0, {}).get("params_sha"),
        "shas": sorted({r.get("params_sha") for r in out.values()}),
    }
    return data, {}   # child injector summaries are not visible here


# --------------------------------------------------------------------------
# schedule generation
# --------------------------------------------------------------------------

def _draw_entry(kind: str, rng: random.Random) -> str:
    h = _HORIZON
    if kind == "bad_batch":
        return f"bad_batch@{rng.randint(1, h - 2)}"
    if kind == "nan":
        return f"nan@{rng.randint(1, h - 2)}"
    if kind == "device":
        code = rng.choice(["UNAVAILABLE", "RESOURCE_EXHAUSTED"])
        return f"device@{rng.randint(1, h - 2)}:{code}"
    if kind == "preempt":
        return f"preempt@{rng.randint(2, h - 3)}"
    if kind == "enospc":
        return f"enospc@{rng.randint(2, h - 2)}"
    if kind == "eio":
        return f"eio@{rng.randint(0, 4)}"
    if kind == "slow_io":
        return f"slow_io@{rng.randint(0, 4)}:{rng.choice([5, 10, 20])}"
    if kind == "rot_shard":
        return "rot_shard@0"
    if kind == "kill_worker":
        return f"kill_worker@{rng.randint(2, 5)}:{rng.randint(0, 1)}"
    if kind == "stall_worker":
        return f"stall_worker@{rng.randint(2, 5)}:{rng.randint(0, 1)}:0.3"
    raise ValueError(f"no draw rule for kind {kind!r}")


def _tpl_train_restart_storage(rng: random.Random) -> str:
    # the adversarial pairing: a storage fault INSIDE the resume window
    # a preemption opens — the replayed save must ride the full-disk out
    p = rng.randint(2, 5)
    return f"preempt@{p};enospc@{rng.randint(p + 1, p + 3)}"


def _tpl_train_numeric_device(rng: random.Random) -> str:
    a, b = rng.sample(range(1, _HORIZON - 2), 2)
    return f"nan@{a};device@{b}:UNAVAILABLE"


def _tpl_online_cadence_enospc(rng: random.Random) -> str:
    # ENOSPC landing exactly ON a publish-cadence step, plus a data fault
    s = _PUBLISH_PERIOD * rng.randint(1, 2)
    return f"enospc@{s};bad_batch@{rng.randint(1, _HORIZON - 2)}"


def _tpl_serving_rot_plus_eio(rng: random.Random) -> str:
    # corrupt snapshot AND a flaky store read in the same publish
    return f"rot_shard@0;eio@{rng.randint(0, 3)}"


def _tpl_gang_kill_then_enospc(rng: random.Random) -> str:
    # storage fault inside the gang-restart replay window
    s = rng.randint(2, 4)
    return f"kill_worker@{s}:1;enospc@{s + 2}:1"


SCENARIOS: Dict[str, Scenario] = {
    "train": Scenario(
        name="train",
        capabilities=("loader", "feed", "dispatch", "io"),
        kinds=("bad_batch", "nan", "device", "preempt",
               "enospc", "eio", "slow_io"),
        runner=_run_train,
        templates=(_tpl_train_restart_storage, _tpl_train_numeric_device)),
    "online": Scenario(
        name="online",
        capabilities=("loader", "feed", "dispatch", "io"),
        kinds=("bad_batch", "nan", "device", "enospc", "eio", "slow_io"),
        runner=_run_online,
        templates=(_tpl_online_cadence_enospc,)),
    "serving": Scenario(
        name="serving",
        capabilities=("io", "commit"),
        kinds=("eio", "slow_io", "rot_shard"),
        runner=_run_serving,
        templates=(_tpl_serving_rot_plus_eio,)),
    "gang": Scenario(
        name="gang",
        capabilities=("loader", "feed", "dispatch", "io", "gang"),
        kinds=("kill_worker", "stall_worker", "enospc"),
        runner=_run_gang,
        templates=(_tpl_gang_kill_then_enospc,),
        smoke=False),
}


def generate_schedule(scenario: str, rng: random.Random,
                      max_faults: int = 3, avoid=()) -> str:
    """One seeded pseudo-random compound schedule for `scenario`,
    guaranteed to pass `validate_schedule` against the scenario's
    capabilities.  Half the draws use an adversarial pairing template,
    half are weighted random compounds.  Specs in `avoid` are redrawn
    (the campaign passes its already-drawn set so one seed covers more
    of the schedule space)."""
    sc = SCENARIOS[scenario]
    last = None
    for _ in range(50):
        if sc.templates and rng.random() < 0.5:
            spec = rng.choice(sc.templates)(rng)
        else:
            n = rng.randint(2, max(2, max_faults))
            spec = ";".join(_draw_entry(rng.choice(sc.kinds), rng)
                            for _ in range(n))
        try:
            validate_schedule(spec, sc.capabilities)
        except ValueError:
            continue   # duplicate / unreachable pairing: redraw
        if spec in avoid:
            last = spec   # fall back to a repeat if the space is tiny
            continue
        return spec
    if last is not None:
        return last
    raise RuntimeError(f"could not draw a valid {scenario} schedule")


# --------------------------------------------------------------------------
# the ordinary single-run path
# --------------------------------------------------------------------------

def run_one(scenario: str, spec: str, seed: int = 0,
            workdir: Optional[str] = None) -> RunResult:
    """Run ONE fault schedule against ONE scenario — the same path the
    campaign, the shrinker, `--replay`, and a human with a copy-pasted
    `FLAGS_fault_spec` all use, so verdicts are reproducible by
    construction.  Deterministic given (scenario, spec, seed)."""
    sc = SCENARIOS[scenario]
    parse_fault_spec(spec)   # fail fast on grammar errors
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="pt-chaos-run-")
    os.makedirs(workdir, exist_ok=True)
    was = _MON.enabled
    if not was:
        _MON.enable()
    before = dict(_MON.counter_values())
    try:
        data, fired = sc.runner(spec, seed, workdir)
        ok, err = True, None
    except Exception as e:   # the crash itself is the verdict
        data, fired = {}, {}
        ok, err = False, f"{type(e).__name__}: {e}"
    after = dict(_MON.counter_values())
    if not was:
        _MON.disable()
    deltas = {k: v - before.get(k, 0) for k, v in after.items()
              if v != before.get(k, 0)}
    return RunResult(scenario=scenario, spec=spec, seed=seed, ok=ok,
                     error=err, fired=fired, data=data, counters=deltas)


# --------------------------------------------------------------------------
# the invariant registry
# --------------------------------------------------------------------------

def _inv_run_completed(run: RunResult) -> Optional[str]:
    if run.ok:
        return None
    return f"scenario crashed instead of surviving: {run.error}"


def _inv_sample_accounting(run: RunResult) -> Optional[str]:
    d = run.data
    expected = d["n_feeds"] - d["dropped_unique"]
    if d["steps"] == expected:
        return None
    return (f"trained {d['steps']} steps but {d['n_feeds']} feeds minus "
            f"{d['dropped_unique']} classified drops = {expected} — a "
            f"sample was silently dropped or double-trained")


def _inv_bit_identical(run: RunResult) -> Optional[str]:
    dv = run.data["diverged_vars"]
    if not dv:
        return None
    return (f"post-recovery state diverged from the uninterrupted arm in "
            f"{len(dv)} var(s): {dv[:4]}")


def _inv_counters_reconciled(run: RunResult) -> Optional[str]:
    bad = []
    for kind, n in run.fired.items():
        got = run.counters.get(f"faults.{kind}", 0)
        if got != n:
            bad.append(f"faults.{kind}={got} but injector fired {n}")
    if run.scenario == "train":
        pre = run.fired.get("preempt", 0)
        got = run.counters.get("resilience.preemptions", 0)
        if got != pre:
            bad.append(f"resilience.preemptions={got} but {pre} preempt "
                       f"fault(s) fired")
    if run.scenario == "online":
        pubs = run.data["publishes"]
        got = run.counters.get("serving.publishes", 0)
        if got != pubs:
            bad.append(f"serving.publishes={got} but stats say {pubs}")
    if not bad:
        return None
    return "monitor counters do not reconcile with events: " + "; ".join(bad)


def _inv_publish_cadence(run: RunResult) -> Optional[str]:
    d = run.data
    expected = (d["steps"] - 1) // d["period"] if d["steps"] else 0
    attempts = d["publishes"] + d["publish_failures"]
    if attempts != expected:
        return (f"cadence broken: {attempts} publish attempts "
                f"({d['publishes']} ok + {d['publish_failures']} failed) "
                f"over {d['steps']} steps at period {d['period']} — "
                f"expected {expected}")
    storage_fires = sum(run.fired.get(k, 0)
                        for k in ("enospc", "eio", "ro_fs"))
    if d["publish_failures"] > storage_fires:
        return (f"{d['publish_failures']} publishes failed but only "
                f"{storage_fires} storage fault(s) fired — a failure "
                f"has no injected cause")
    return None


def _inv_serving_ledger(run: RunResult) -> Optional[str]:
    led = run.data["ledger"]
    if led["balanced"]:
        return None
    terms = " + ".join(f"{k}={led[k]}" for k in
                       ("completed", "shed", "timeouts", "errors",
                        "shutdowns"))
    return (f"serving ledger identity broken: requests={led['requests']} "
            f"!= {terms}")


def _inv_no_good_quarantine(run: RunResult) -> Optional[str]:
    d = run.data
    if d["rot_fired"] and not d["quarantined"]:
        return "a rotted snapshot was published without quarantine"
    if d["rot_fired"] and d["publish_ok"]:
        return "a rotted snapshot was activated"
    if not d["rot_fired"] and d["quarantined"]:
        return "a GOOD snapshot was quarantined"
    return None


def _inv_active_version(run: RunResult) -> Optional[str]:
    if run.data["served_scale_ok"]:
        return None
    side = ("new" if run.data["publish_ok"] else "last-good")
    return (f"post-publish traffic is not served by the {side} version "
            f"(closed-form output mismatch)")


def _inv_gang_bit_identical(run: RunResult) -> Optional[str]:
    d = run.data
    if not d["ref_ok"]:
        return "reference gang did not converge (environment problem)"
    if not d["ok"]:
        return "chaos gang did not converge"
    if len(d["shas"]) != 1 or d["shas"][0] != d["ref_sha"]:
        return (f"gang end-state diverged: chaos {d['shas']} vs "
                f"reference {d['ref_sha']}")
    return None


@dataclass
class Invariant:
    name: str
    scenarios: Tuple[str, ...]
    cls: str
    check: Callable[[RunResult], Optional[str]]


INVARIANTS: List[Invariant] = [
    Invariant("run_completed", ("train", "online", "serving", "gang"),
              "crash", _inv_run_completed),
    Invariant("sample_accounting", ("train", "online"),
              "ledger", _inv_sample_accounting),
    Invariant("bit_identical_recovery", ("train",),
              "recovery", _inv_bit_identical),
    Invariant("counters_reconciled", ("train", "online"),
              "accounting", _inv_counters_reconciled),
    Invariant("publish_cadence", ("online",),
              "cadence", _inv_publish_cadence),
    Invariant("ledger_exact", ("serving",),
              "ledger", _inv_serving_ledger),
    Invariant("no_good_snapshot_quarantined", ("serving",),
              "quarantine", _inv_no_good_quarantine),
    Invariant("active_version_correct", ("serving",),
              "recovery", _inv_active_version),
    Invariant("gang_bit_identical", ("gang",),
              "recovery", _inv_gang_bit_identical),
]


def invariants_for(scenario: str) -> List[Invariant]:
    return [iv for iv in INVARIANTS if scenario in iv.scenarios]


def evaluate(run: RunResult) -> List[Violation]:
    """Evaluate every applicable invariant over the run.  A crashed run
    yields exactly the run_completed violation (the probes the other
    invariants need do not exist)."""
    if not run.ok:
        return [Violation("run_completed", "crash",
                          _inv_run_completed(run))]
    out = []
    for iv in invariants_for(run.scenario):
        msg = iv.check(run)
        if msg is not None:
            out.append(Violation(iv.name, iv.cls, msg))
    return out


# --------------------------------------------------------------------------
# the shrinker
# --------------------------------------------------------------------------

def _render(faults) -> str:
    return ";".join(str(f) for f in faults)


def shrink(scenario: str, spec: str, seed: int, invariant: str,
           max_runs: int = 24,
           workdir: Optional[str] = None) -> ShrinkResult:
    """Reduce a failing schedule to a minimal still-failing
    `FLAGS_fault_spec`: greedy fault-removal (drop any entry whose
    absence still violates `invariant`) then step-bisection (halve each
    surviving entry's index while the violation persists).  Every
    candidate is re-verified through `run_one` — the ordinary path —
    so the shrunk spec is replayable as-is."""
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="pt-chaos-shrink-")
    runs = 0

    def fails(s: str) -> bool:
        nonlocal runs
        runs += 1
        d = os.path.join(workdir, f"probe-{runs}")
        r = run_one(scenario, s, seed=seed, workdir=d)
        return any(v.invariant == invariant for v in evaluate(r))

    faults = parse_fault_spec(spec)
    changed = True
    while changed and runs < max_runs:
        changed = False
        for i in range(len(faults)):
            if len(faults) == 1:
                break
            cand = faults[:i] + faults[i + 1:]
            if fails(_render(cand)):
                faults = cand
                changed = True
                break
            if runs >= max_runs:
                break
    for f in faults:
        while f.at > 0 and runs < max_runs:
            old = f.at
            f.at = old // 2
            if _render(faults).count(str(f)) > 1 or not fails(_render(faults)):
                f.at = old
                break
    return ShrinkResult(spec=_render(faults), runs=runs,
                        converged=runs < max_runs)


# --------------------------------------------------------------------------
# the campaign driver
# --------------------------------------------------------------------------

def run_campaign(scenarios=("train", "online", "serving"), seed: int = 0,
                 per_scenario: int = 2, out_dir: Optional[str] = None,
                 metrics_path: Optional[str] = None, do_shrink: bool = True,
                 max_faults: int = 3) -> CampaignResult:
    """Generate and run `per_scenario` seeded schedules per scenario,
    evaluate the invariant registry after each, shrink failures to
    minimal repro specs, and emit `CHAOS_REPRO.json` artifacts plus
    chaos_event records / chaos.* counters (written to `metrics_path`
    as JSONL when given — the file `perf_report --check
    --max-chaos-violations` gates on)."""
    from .monitor import MonitorLogger, attach_logger, detach_logger, \
        record_step

    class _ChaosLogger(MonitorLogger):
        """Forward only chaos_event records.  The campaign's scenario
        runs emit executor step records from dozens of unrelated tiny
        programs; letting those into the metrics file would trip
        perf_report's recompile-flatness gate on churn the campaign
        caused on purpose.  Snapshots (counters/gauges) pass through
        unchanged — they carry the chaos.* evidence the
        --max-chaos-violations gate reads."""

        def on_step(self, record):
            if record.get("kind") == "chaos_event":
                super().on_step(record)

    sweep_stale_ledgers()
    out_dir = out_dir or tempfile.mkdtemp(prefix="pt-chaos-campaign-")
    os.makedirs(out_dir, exist_ok=True)
    res = CampaignResult(out_dir=out_dir, metrics_path=metrics_path)
    was = _MON.enabled
    if not was:
        _MON.enable()
    logger = None
    if metrics_path:
        logger = attach_logger(_ChaosLogger(metrics_path))
    rng = random.Random(seed)
    drawn: set = set()
    try:
        for sname in scenarios:
            for i in range(per_scenario):
                spec = generate_schedule(sname, rng, max_faults,
                                         avoid=drawn)
                drawn.add(spec)
                run = run_one(sname, spec, seed=seed,
                              workdir=os.path.join(out_dir, f"{sname}-{i}"))
                vs = evaluate(run)
                checked = (len(invariants_for(sname)) if run.ok else 1)
                res.schedules_run += 1
                res.invariants_checked += checked
                _MON.counter("chaos.schedules_run").inc()
                _MON.counter("chaos.invariants_checked").inc(checked)
                verdict = "fail" if vs else "pass"
                record_step({"kind": "chaos_event", "event": "schedule",
                             "scenario": sname, "spec": spec, "seed": seed,
                             "verdict": verdict,
                             "invariant": vs[0].invariant if vs else None,
                             "class": vs[0].cls if vs else None,
                             "faults_fired": sum(run.fired.values())})
                res.schedules.append({"scenario": sname, "spec": spec,
                                      "seed": seed, "verdict": verdict})
                if not vs:
                    continue
                _MON.counter("chaos.invariant_violations").inc(len(vs))
                for v in vs:
                    entry = {"scenario": sname, "spec": spec, "seed": seed,
                             "invariant": v.invariant, "class": v.cls,
                             "message": v.message}
                    if do_shrink:
                        sh = shrink(sname, spec, seed, v.invariant,
                                    workdir=os.path.join(
                                        out_dir, f"{sname}-{i}-shrink"))
                        entry["shrunk_spec"] = sh.spec
                        entry["shrink_runs"] = sh.runs
                        entry["shrink_converged"] = sh.converged
                        record_step({"kind": "chaos_event",
                                     "event": "shrunk", "scenario": sname,
                                     "spec": spec, "shrunk_spec": sh.spec,
                                     "invariant": v.invariant,
                                     "probe_runs": sh.runs})
                    repro = dict(entry)
                    repro["replay"] = (
                        f"python tools/chaos_campaign.py --replay "
                        f"--scenario {sname} --seed {seed} "
                        f"--spec '{entry.get('shrunk_spec', spec)}'")
                    rp = os.path.join(
                        out_dir,
                        f"CHAOS_REPRO-{len(res.repro_paths)}.json")
                    with open(rp, "w") as fh:
                        json.dump(repro, fh, indent=2, sort_keys=True)
                    res.repro_paths.append(rp)
                    res.violations.append(entry)
        with open(os.path.join(out_dir, "CAMPAIGN.json"), "w") as fh:
            json.dump({"seed": seed, "schedules": res.schedules,
                       "schedules_run": res.schedules_run,
                       "invariants_checked": res.invariants_checked,
                       "violations": res.violations},
                      fh, indent=2, sort_keys=True)
    finally:
        if logger is not None:
            logger.write_snapshot()
            detach_logger(logger)
            logger.close()
        if not was:
            _MON.disable()
    return res
