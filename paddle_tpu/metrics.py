"""Metrics (reference: python/paddle/fluid/metrics.py — MetricBase,
Accuracy, Precision, Recall, Auc, EditDistance, CompositeMetric,
DetectionMAP).  Host-side accumulators over fetched numpy values, same
update/eval contract as the reference."""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class MetricBase:
    def __init__(self, name: Optional[str] = None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0 if isinstance(v, int) else 0.0)
            elif isinstance(v, list):
                setattr(self, k, [])

    def update(self, *a, **k):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        value = float(np.asarray(value).reshape(-1)[0])
        weight = float(np.asarray(weight).reshape(-1)[0])
        if weight < 0:
            raise ValueError("weight must be non-negative")
        self.value += value * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no samples accumulated")
        return self.value / self.weight


class Precision(MetricBase):
    """Binary precision (reference metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").reshape(-1)
        labels = np.asarray(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        ap = self.tp + self.fp
        return self.tp / ap if ap else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").reshape(-1)
        labels = np.asarray(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Threshold-bucketed ROC AUC (reference metrics.py Auc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(num_thresholds + 1, dtype=np.int64)

    def reset(self):
        self._stat_pos[:] = 0
        self._stat_neg[:] = 0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] > 1 else preds.reshape(-1)
        buckets = np.clip((pos_prob * self._num_thresholds).astype(np.int64), 0, self._num_thresholds)
        for b, l in zip(buckets, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            p, n = self._stat_pos[i], self._stat_neg[i]
            auc += n * (tot_pos + p / 2.0)
            tot_pos += p
            tot_neg += n
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances, dtype=np.float64).reshape(-1)
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((d > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no data")
        return self.total_distance / self.seq_num, self.instance_error / self.seq_num


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics: List[MetricBase] = []

    def add_metric(self, metric: MetricBase):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


def edit_distance_np(a: str, b: str) -> int:
    """Levenshtein distance helper (reference computes it in edit_distance_op)."""
    la, lb = len(a), len(b)
    dp = np.arange(lb + 1)
    for i in range(1, la + 1):
        prev = dp.copy()
        dp[0] = i
        for j in range(1, lb + 1):
            dp[j] = min(prev[j] + 1, dp[j - 1] + 1, prev[j - 1] + (a[i - 1] != b[j - 1]))
    return int(dp[lb])
