"""Inference predictor: compile-and-serve of saved inference models.

Reference: AnalysisPredictor (inference/api/analysis_predictor.h:46) +
AnalysisConfig (inference/api/paddle_analysis_config.h) + ZeroCopyTensor
(inference/api/paddle_inference_api.h) — load a saved __model__ + params,
run analysis passes, serve Run() calls, clone() per serving thread, and
expose input/output buffers without feed/fetch copies.

TPU-first: the "analysis passes" are XLA (whole-program fusion happens at
compile, so the reference's fuse pass pipeline has no residue to apply);
the predictor is a pruned Program + Scope + Executor with the compiled
executable cached after the first call.  clone() shares the weights
(read-only Scope) AND the Executor — so every clone serves from the same
compiled-executable cache entry per (program, feed-shape) signature and N
clones never compile N times (XLA executables are thread-safe; the
reference's clone-per-thread contract kept for the handle dicts, which
stay private per clone).  Thread safety: `run`/`run_zero_copy` hold a
per-predictor lock — the staged input/output handle dicts are shared
mutable state, and two unsynchronized threads interleaving stage/execute/
read would serve each other's tensors.  Concurrency scales by cloning
(one predictor per thread), not by hammering one predictor from many.
Int8 models saved via
io.save_quantized_inference_model load transparently (weights dequantize
from their int8 grid at load; the served numerics ARE the int8-representable
values)."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .core import locks
from .core.executor import CPUPlace, Executor, Place, TPUPlace
from .core.program import Program
from .core.scope import Scope
from . import io as _io


class AnalysisConfig:
    """reference paddle_analysis_config.h, mapped to what exists on TPU.

    Knobs that are XLA's job are accepted-and-recorded no-ops so reference
    configs port without edits; each says so in its docstring."""

    def __init__(self, model_dir: str, place: Optional[Place] = None):
        self.model_dir = model_dir
        self.place = place or TPUPlace(0)
        self._ir_optim = True
        self._memory_optim = True
        self._int8 = True  # quantized models auto-detected at load
        self._threads = 1

    # -- device selection -------------------------------------------------
    def enable_tpu(self, device_id: int = 0):
        """reference enable_use_gpu analog."""
        self.place = TPUPlace(device_id)
        return self

    def disable_tpu(self):
        self.place = CPUPlace()
        return self

    # -- optimization switches (XLA-subsumed; recorded for parity) --------
    def switch_ir_optim(self, on: bool = True):
        """reference pass-pipeline switch: XLA always optimizes — recorded
        only (a False here does not produce an unoptimized executable)."""
        self._ir_optim = bool(on)
        return self

    def enable_memory_optim(self, on: bool = True):
        """reference memory-reuse pass: PJRT buffer donation is always on
        for inference (no state write-back); recorded only."""
        self._memory_optim = bool(on)
        return self

    def set_cpu_math_library_num_threads(self, n: int):
        """reference MKL thread knob: XLA:CPU threading is process-global;
        recorded only."""
        self._threads = int(n)
        return self

    def enable_quantize(self, on: bool = True):
        """int8 models are detected from __quant__.json automatically; this
        records intent for config introspection."""
        self._int8 = bool(on)
        return self

    def summary(self) -> dict:
        return {"model_dir": self.model_dir, "place": type(self.place).__name__,
                "ir_optim": self._ir_optim, "memory_optim": self._memory_optim,
                "int8": self._int8, "threads": self._threads}


# backward-compatible alias (round-4 surface)
PredictConfig = AnalysisConfig


class PredictorTensor:
    """reference ZeroCopyTensor: a named input/output buffer handle.

    copy_from_cpu COPIES the host array (the reference contract: mutating
    the source buffer afterwards must not change the staged feed);
    share_external_data is the zero-copy alias path — a DataLoader or
    upstream model output already on device is adopted untouched.
    copy_to_cpu materializes the result to numpy once."""

    def __init__(self, name: str):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(np.array(arr, copy=True))
        return self

    def share_external_data(self, jax_array):
        """Adopt a device-resident array without copying."""
        self._value = jax_array
        return self

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise ValueError(f"output {self.name!r} not produced yet; "
                             "call Predictor.run_zero_copy() first")
        return np.asarray(self._value)

    def value(self):
        """The raw (possibly device-resident) array — no host copy."""
        return self._value


class Predictor:
    def __init__(self, config: AnalysisConfig, _shared=None,
                 executor: Optional[Executor] = None):
        self.config = config
        if _shared is not None:  # clone path: share program + weights
            self.program, self.feed_names, self.fetch_names, self.scope = _shared
        else:
            self.scope = Scope()
            exe = Executor(config.place)
            self.program, self.feed_names, self.fetch_names = _io.load_inference_model(
                config.model_dir, exe, scope=self.scope)
        # `executor` shares a compiled-executable cache across predictors:
        # clone() passes its own, and the serving model registry
        # (paddle_tpu/serving/registry.py) passes ONE executor for every
        # model/version so each (program, bucket shape) signature compiles
        # exactly once however many clones/versions serve it
        self.exe = executor if executor is not None else Executor(config.place)
        # run/run_zero_copy are serialized per predictor: the staged
        # input/output handle dicts are shared mutable state (the
        # reference's contract was clone-per-thread; we keep that as the
        # scaling path and make the single-predictor path safe instead of
        # silently racy)
        self._lock = locks.named_rlock("inference.predictor", rank=20)
        self._inputs = {n: PredictorTensor(n) for n in self.feed_names}
        self._outputs = {n: PredictorTensor(n) for n in self.fetch_names}

    def lock(self) -> "locks.NamedLock":
        """The per-predictor serialization lock (re-entrant).  `run` and
        `run_zero_copy` take it internally, which makes the dict API
        atomic — but a zero-copy TRANSACTION spans three calls
        (copy_from_cpu -> run_zero_copy -> copy_to_cpu), so threads
        sharing one predictor must hold this lock across the whole
        sequence:

            with predictor.lock():
                predictor.get_input_handle("x").copy_from_cpu(arr)
                predictor.run_zero_copy()
                out = predictor.get_output_handle(name).copy_to_cpu()

        Or — the contract that actually scales — clone() per thread."""
        return self._lock

    # -- classic dict API --------------------------------------------------
    def run(self, feeds: Dict[str, np.ndarray],
            fetch_names: Optional[Sequence[str]] = None,
            return_numpy: bool = True) -> List[np.ndarray]:
        missing = set(self.feed_names) - set(feeds)
        if missing:
            raise KeyError(f"Predictor.run: missing feeds {sorted(missing)}")
        with self._lock:  # lock-ok: serializing dispatch (compile included) per predictor IS the lock's documented contract; clone-per-thread is the concurrency path and shares the compiled-executable cache
            return self.exe.run(
                self.program, feed=dict(feeds),
                fetch_list=list(fetch_names or self.fetch_names), scope=self.scope,
                return_numpy=return_numpy)

    # -- zero-copy handle API (reference ZeroCopyRun contract) -------------
    def get_input_names(self) -> List[str]:
        return list(self.feed_names)

    def get_output_names(self) -> List[str]:
        return list(self.fetch_names)

    def get_input_handle(self, name: str) -> PredictorTensor:
        return self._inputs[name]

    def get_output_handle(self, name: str) -> PredictorTensor:
        return self._outputs[name]

    def run_zero_copy(self):
        """Execute from the staged input handles into the output handles.
        Device-resident inputs pass straight to the executor (no host
        round-trip); outputs stay device-resident until copy_to_cpu.
        Serialized per predictor (the handle dicts are shared state);
        concurrent serving threads should each hold a clone()."""
        with self._lock:  # lock-ok: same per-predictor serialization contract as run(); the staged handle dicts are the shared state being protected
            feeds = {}
            for n, h in self._inputs.items():
                if h._value is None:
                    raise KeyError(f"input handle {n!r} has no data; call "
                                   "copy_from_cpu/share_external_data first")
                feeds[n] = h._value
            outs = self.exe.run(self.program, feed=feeds,
                                fetch_list=list(self.fetch_names),
                                scope=self.scope, return_numpy=False)
            for n, v in zip(self.fetch_names, outs):
                self._outputs[n]._value = v
            return True

    def clone(self) -> "Predictor":
        """Serve from another thread: shared weights, SHARED executor —
        every clone hits the same compiled-executable cache entry per
        (program, feed signature), so N clones compile once (XLA
        executables are thread-safe; pinned by the serving cache-share
        test).  Handle dicts and the run lock stay private per clone."""
        return Predictor(self.config, _shared=(
            self.program, self.feed_names, self.fetch_names, self.scope),
            executor=self.exe)


def create_predictor(config: AnalysisConfig) -> Predictor:
    """reference CreatePaddlePredictor."""
    return Predictor(config)
