"""Inference predictor: compile-and-serve of saved inference models.

Reference: AnalysisPredictor (inference/api/analysis_predictor.h:46) —
load a saved __model__ + params, run analysis passes, serve Run() calls,
clone() per serving thread.

TPU-first: the "analysis passes" are XLA (whole-program fusion happens at
compile, so the reference's fuse pass pipeline has no residue to apply);
the predictor is a pruned Program + Scope + Executor with the compiled
executable cached after the first call.  clone() shares the weights
(read-only Scope) but gets its own Executor — the reference's
clone-per-thread contract."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.executor import CPUPlace, Executor, Place, TPUPlace
from .core.program import Program
from .core.scope import Scope
from . import io as _io


class PredictConfig:
    """reference AnalysisConfig (trimmed to what matters on TPU)."""

    def __init__(self, model_dir: str, place: Optional[Place] = None):
        self.model_dir = model_dir
        self.place = place or TPUPlace(0)


class Predictor:
    def __init__(self, config: PredictConfig, _shared=None):
        self.config = config
        if _shared is not None:  # clone path: share program + weights
            self.program, self.feed_names, self.fetch_names, self.scope = _shared
        else:
            self.scope = Scope()
            exe = Executor(config.place)
            self.program, self.feed_names, self.fetch_names = _io.load_inference_model(
                config.model_dir, exe, scope=self.scope)
        self.exe = Executor(config.place)

    def run(self, feeds: Dict[str, np.ndarray],
            fetch_names: Optional[Sequence[str]] = None) -> List[np.ndarray]:
        missing = set(self.feed_names) - set(feeds)
        if missing:
            raise KeyError(f"Predictor.run: missing feeds {sorted(missing)}")
        return self.exe.run(
            self.program, feed=dict(feeds),
            fetch_list=list(fetch_names or self.fetch_names), scope=self.scope)

    def clone(self) -> "Predictor":
        """Serve from another thread: shared weights, private executor
        (compile cache is per-executor; XLA executables are thread-safe)."""
        return Predictor(self.config, _shared=(
            self.program, self.feed_names, self.fetch_names, self.scope))


def create_predictor(config: PredictConfig) -> Predictor:
    """reference CreatePaddlePredictor."""
    return Predictor(config)
