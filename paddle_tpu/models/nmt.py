"""Transformer-base NMT on the ragged/LoD path (BASELINE.md target).

Reference: `tests/unittests/dist_transformer.py` + the LoD machine-translation
benchmark (`benchmark/fluid/machine_translation.py`).  The reference feeds
host-built attention-bias tensors computed from the LoD; here ragged src/tgt
feed as `fluid.LoDTensor` and every mask/bias derives inside the compiled
program from the lengths companions (layers.attention_bias), so bucketed
padded batches recompile only per bucket, not per shape.

Time dims are dynamic at build time (shape -1): head split/merge reshapes
use fluid's `0` (copy-dim) semantics, so one build serves every bucket.
"""
from __future__ import annotations

from .. import layers, optimizer
from ..core.program import Program, program_guard
from .transformer import _attr, multi_head_attention


def _mha(q_in, kv_in, bias, d_model, n_heads, prefix, dropout=0.1, is_test=False):
    """Cross/self attention with additive bias (shared transformer builder)."""
    return multi_head_attention(q_in, None, d_model, n_heads, prefix,
                                dropout_prob=dropout, is_test=is_test,
                                kv=None if kv_in is q_in else kv_in, bias=bias)


def _ffn(x, d_model, d_ff, prefix, dropout=0.1, is_test=False):
    h = layers.fc(x, d_ff, num_flatten_dims=2, act="relu",
                  param_attr=_attr(f"{prefix}.fc1.w"), bias_attr=_attr(f"{prefix}.fc1.b"))
    if dropout and not is_test:
        h = layers.dropout(h, dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, d_model, num_flatten_dims=2,
                     param_attr=_attr(f"{prefix}.fc2.w"), bias_attr=_attr(f"{prefix}.fc2.b"))


def _add_norm(x, y, prefix, dropout=0.1, is_test=False):
    if dropout and not is_test:
        y = layers.dropout(y, dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    out = layers.elementwise_add(x, y)
    return layers.layer_norm(out, begin_norm_axis=2,
                             param_attr=_attr(f"{prefix}.ln.w"), bias_attr=_attr(f"{prefix}.ln.b"))


def _embed(ids, vocab, d_model, prefix, dropout=0.1, is_test=False):
    # lengths companion propagates through each of these (layers._keep_lod)
    emb = layers.embedding(ids, size=[vocab, d_model], param_attr=_attr(f"{prefix}.emb"))
    emb = layers.scale(emb, scale=float(d_model) ** 0.5)
    emb = layers.position_encoding(emb)
    if dropout and not is_test:
        emb = layers.dropout(emb, dropout, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    return emb


def build_transformer_nmt(
    src_vocab=1000,
    tgt_vocab=1000,
    d_model=256,
    n_layers=2,
    n_heads=4,
    d_ff=1024,
    dropout=0.1,
    label_smooth_eps=0.1,
    learning_rate=2.0,
    warmup_steps=400,
    with_optimizer=True,
    is_test=False,
):
    """Returns (main, startup, feeds, fetches).

    Feeds: src_word [b,Ts,1] int64 ragged; trg_word [b,Tt,1] int64 ragged
    (decoder input, <bos>-shifted); lbl_word [b,Tt,1] int64 ragged (targets).
    Loss is per-token cross entropy with label smoothing, masked to each
    row's length and normalized by the total token count.
    """
    main, startup = Program(), Program()
    with program_guard(main, startup):
        src = layers.data("src_word", [1], dtype="int64", lod_level=1)
        tgt = layers.data("trg_word", [1], dtype="int64", lod_level=1)
        lbl = layers.data("lbl_word", [1], dtype="int64", lod_level=1)

        enc = _embed(src, src_vocab, d_model, "src", dropout, is_test)
        enc_bias = layers.attention_bias(enc, enc, causal=False)
        for i in range(n_layers):
            p = f"enc{i}"
            enc = _add_norm(enc, _mha(enc, enc, enc_bias, d_model, n_heads,
                                      f"{p}.attn", dropout, is_test), f"{p}.a", dropout, is_test)
            enc = _add_norm(enc, _ffn(enc, d_model, d_ff, f"{p}.ffn", dropout, is_test),
                            f"{p}.f", dropout, is_test)

        dec = _embed(tgt, tgt_vocab, d_model, "tgt", dropout, is_test)
        self_bias = layers.attention_bias(dec, dec, causal=True)
        cross_bias = layers.attention_bias(dec, enc, causal=False)
        for i in range(n_layers):
            p = f"dec{i}"
            dec = _add_norm(dec, _mha(dec, dec, self_bias, d_model, n_heads,
                                      f"{p}.self", dropout, is_test), f"{p}.s", dropout, is_test)
            dec = _add_norm(dec, _mha(dec, enc, cross_bias, d_model, n_heads,
                                      f"{p}.cross", dropout, is_test), f"{p}.c", dropout, is_test)
            dec = _add_norm(dec, _ffn(dec, d_model, d_ff, f"{p}.ffn", dropout, is_test),
                            f"{p}.f", dropout, is_test)

        logits = layers.fc(dec, tgt_vocab, num_flatten_dims=2,
                           param_attr=_attr("proj.w"), bias_attr=_attr("proj.b"))

        if label_smooth_eps:
            smooth = layers.label_smooth(layers.one_hot(lbl, tgt_vocab),
                                         epsilon=label_smooth_eps)
            ce = layers.softmax_with_cross_entropy(logits, smooth, soft_label=True)
        else:
            ce = layers.softmax_with_cross_entropy(logits, lbl)
        # ce inherits the decoder side's raggedness (logits carry tgt's
        # lengths companion); the sum pool masks beyond each row's length
        per_sent = layers.sequence_pool(ce, "sum")  # [b, 1]
        total = layers.reduce_sum(per_sent)
        ntok = layers.reduce_sum(layers.cast(tgt._lod_ref, "float32"))
        loss = layers.elementwise_div(total, ntok)

        if with_optimizer:
            lr = layers.noam_decay(d_model, warmup_steps, learning_rate)
            optimizer.Adam(learning_rate=lr, beta1=0.9, beta2=0.997,
                           epsilon=1e-9).minimize(loss)

    feeds = {"src_word": src, "trg_word": tgt, "lbl_word": lbl}
    return main, startup, feeds, {"loss": loss, "logits": logits}


def make_fake_nmt_batch(lengths_src, lengths_tgt, src_vocab, tgt_vocab, seed=0):
    """Ragged fake batch: returns the feed dict of LoDTensors."""
    import numpy as np

    from ..lod import LoDTensor

    rng = np.random.RandomState(seed)
    src = [rng.randint(1, src_vocab, (l, 1)).astype("int64") for l in lengths_src]
    tgt = [rng.randint(1, tgt_vocab, (l, 1)).astype("int64") for l in lengths_tgt]
    lbl = [rng.randint(1, tgt_vocab, (l, 1)).astype("int64") for l in lengths_tgt]
    return {"src_word": LoDTensor(src), "trg_word": LoDTensor(tgt), "lbl_word": LoDTensor(lbl)}
