"""Transformer-base NMT on the ragged/LoD path (BASELINE.md target).

Reference: `tests/unittests/dist_transformer.py` + the LoD machine-translation
benchmark (`benchmark/fluid/machine_translation.py`).  The reference feeds
host-built attention-bias tensors computed from the LoD; here ragged src/tgt
feed as `fluid.LoDTensor` and every mask/bias derives inside the compiled
program from the lengths companions (layers.attention_bias), so bucketed
padded batches recompile only per bucket, not per shape.

Time dims are dynamic at build time (shape -1): head split/merge reshapes
use fluid's `0` (copy-dim) semantics, so one build serves every bucket.
"""
from __future__ import annotations

from .. import layers, optimizer
from ..core.program import Program, program_guard
from .transformer import _attr, multi_head_attention


def _mha(q_in, kv_in, bias, d_model, n_heads, prefix, dropout=0.1, is_test=False):
    """Cross/self attention with additive bias (shared transformer builder)."""
    return multi_head_attention(q_in, None, d_model, n_heads, prefix,
                                dropout_prob=dropout, is_test=is_test,
                                kv=None if kv_in is q_in else kv_in, bias=bias)


def _ffn(x, d_model, d_ff, prefix, dropout=0.1, is_test=False):
    h = layers.fc(x, d_ff, num_flatten_dims=2, act="relu",
                  param_attr=_attr(f"{prefix}.fc1.w"), bias_attr=_attr(f"{prefix}.fc1.b"))
    if dropout and not is_test:
        h = layers.dropout(h, dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, d_model, num_flatten_dims=2,
                     param_attr=_attr(f"{prefix}.fc2.w"), bias_attr=_attr(f"{prefix}.fc2.b"))


def _add_norm(x, y, prefix, dropout=0.1, is_test=False):
    if dropout and not is_test:
        y = layers.dropout(y, dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    out = layers.elementwise_add(x, y)
    return layers.layer_norm(out, begin_norm_axis=2,
                             param_attr=_attr(f"{prefix}.ln.w"), bias_attr=_attr(f"{prefix}.ln.b"))


def _embed(ids, vocab, d_model, prefix, dropout=0.1, is_test=False):
    # lengths companion propagates through each of these (layers._keep_lod)
    emb = layers.embedding(ids, size=[vocab, d_model], param_attr=_attr(f"{prefix}.emb"))
    emb = layers.scale(emb, scale=float(d_model) ** 0.5)
    emb = layers.position_encoding(emb)
    if dropout and not is_test:
        emb = layers.dropout(emb, dropout, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    return emb


def build_transformer_nmt(
    src_vocab=1000,
    tgt_vocab=1000,
    d_model=256,
    n_layers=2,
    n_heads=4,
    d_ff=1024,
    dropout=0.1,
    label_smooth_eps=0.1,
    learning_rate=2.0,
    warmup_steps=400,
    with_optimizer=True,
    is_test=False,
    dtype="float32",
):
    """Returns (main, startup, feeds, fetches).

    Feeds: src_word [b,Ts,1] int64 ragged; trg_word [b,Tt,1] int64 ragged
    (decoder input, <bos>-shifted); lbl_word [b,Tt,1] int64 ragged (targets).
    Loss is per-token cross entropy with label smoothing, masked to each
    row's length and normalized by the total token count.
    """
    main, startup = Program(), Program()
    with program_guard(main, startup):
        src = layers.data("src_word", [1], dtype="int64", lod_level=1)
        tgt = layers.data("trg_word", [1], dtype="int64", lod_level=1)
        lbl = layers.data("lbl_word", [1], dtype="int64", lod_level=1)

        enc = _embed(src, src_vocab, d_model, "src", dropout, is_test)
        enc_bias = layers.attention_bias(enc, enc, causal=False)

        def _to_compute(v):
            # bf16 compute path (same recipe as build_bert): one cast on the
            # activations; master weights stay f32 via per-op match_dtype,
            # and biases stay f32 (the add's match_dtype casts them in)
            if dtype == "float32":
                return v
            from ..layers.nn import _keep_lod

            return _keep_lod(v, layers.cast(v, dtype))

        enc = _to_compute(enc)
        for i in range(n_layers):
            p = f"enc{i}"
            enc = _add_norm(enc, _mha(enc, enc, enc_bias, d_model, n_heads,
                                      f"{p}.attn", dropout, is_test), f"{p}.a", dropout, is_test)
            enc = _add_norm(enc, _ffn(enc, d_model, d_ff, f"{p}.ffn", dropout, is_test),
                            f"{p}.f", dropout, is_test)

        dec = _embed(tgt, tgt_vocab, d_model, "tgt", dropout, is_test)
        self_bias = layers.attention_bias(dec, dec, causal=True)
        cross_bias = layers.attention_bias(dec, enc, causal=False)
        dec = _to_compute(dec)
        for i in range(n_layers):
            p = f"dec{i}"
            dec = _add_norm(dec, _mha(dec, dec, self_bias, d_model, n_heads,
                                      f"{p}.self", dropout, is_test), f"{p}.s", dropout, is_test)
            dec = _add_norm(dec, _mha(dec, enc, cross_bias, d_model, n_heads,
                                      f"{p}.cross", dropout, is_test), f"{p}.c", dropout, is_test)
            dec = _add_norm(dec, _ffn(dec, d_model, d_ff, f"{p}.ffn", dropout, is_test),
                            f"{p}.f", dropout, is_test)

        logits = layers.fc(dec, tgt_vocab, num_flatten_dims=2,
                           param_attr=_attr("proj.w"), bias_attr=_attr("proj.b"))
        if dtype != "float32":
            from ..layers.nn import _keep_lod

            logits = _keep_lod(logits, layers.cast(logits, "float32"))

        if label_smooth_eps:
            smooth = layers.label_smooth(layers.one_hot(lbl, tgt_vocab),
                                         epsilon=label_smooth_eps)
            ce = layers.softmax_with_cross_entropy(logits, smooth, soft_label=True)
        else:
            ce = layers.softmax_with_cross_entropy(logits, lbl)
        # ce inherits the decoder side's raggedness (logits carry tgt's
        # lengths companion); the sum pool masks beyond each row's length
        per_sent = layers.sequence_pool(ce, "sum")  # [b, 1]
        total = layers.reduce_sum(per_sent)
        ntok = layers.reduce_sum(layers.cast(tgt._lod_ref, "float32"))
        loss = layers.elementwise_div(total, ntok)

        if with_optimizer:
            lr = layers.noam_decay(d_model, warmup_steps, learning_rate)
            optimizer.Adam(learning_rate=lr, beta1=0.9, beta2=0.997,
                           epsilon=1e-9).minimize(loss)

    feeds = {"src_word": src, "trg_word": tgt, "lbl_word": lbl}
    return main, startup, feeds, {"loss": loss, "logits": logits}


def make_fake_nmt_batch(lengths_src, lengths_tgt, src_vocab, tgt_vocab, seed=0):
    """Ragged fake batch: returns the feed dict of LoDTensors."""
    import numpy as np

    from ..lod import LoDTensor

    rng = np.random.RandomState(seed)
    src = [rng.randint(1, src_vocab, (l, 1)).astype("int64") for l in lengths_src]
    tgt = [rng.randint(1, tgt_vocab, (l, 1)).astype("int64") for l in lengths_tgt]
    lbl = [rng.randint(1, tgt_vocab, (l, 1)).astype("int64") for l in lengths_tgt]
    return {"src_word": LoDTensor(src), "trg_word": LoDTensor(tgt), "lbl_word": LoDTensor(lbl)}


def build_nmt_infer(**kw):
    """Inference-mode NMT program (no optimizer, no dropout, no label loss);
    fetches logits [b, Tt, V].  Used by beam_search_decode."""
    kw.update(with_optimizer=False, is_test=True, dropout=0.0, label_smooth_eps=0.0)
    return build_transformer_nmt(**kw)


def beam_search_decode(exe, infer_program, logits_var, scope, src_rows,
                       bos=1, eos=2, beam_size=4, max_len=12, length_penalty=0.0):
    """Static-shape beam search (reference capability:
    operators/math/beam_search.cu + layers/nn.py beam_search, which walked a
    LoDTensorArray; here every device step is the SAME padded-shape decoder
    program — one compile, max_len dispatches — and the beam bookkeeping is
    trivial host math).

    src_rows: list of np [Ls,1] int64 source sentences (one per batch row).
    Returns (sequences [b, max_len] int64, scores [b]) — best beam per row.
    beam_size=1 is exact greedy decode.
    """
    import numpy as np

    from ..lod import LoDTensor

    b = len(src_rows)
    k = beam_size
    # source repeats per beam: row-major [b*k]
    src_beam = [src_rows[i // k] for i in range(b * k)]

    seqs = np.full((b, k, max_len), eos, dtype="int64")
    seqs[:, :, 0] = bos
    scores = np.full((b, k), -1e9, dtype="float64")
    scores[:, 0] = 0.0  # only beam 0 alive at t=0 (all beams identical)
    finished = np.zeros((b, k), dtype=bool)

    for t in range(1, max_len):
        prefix = seqs.reshape(b * k, max_len)[:, :t]  # [bk, t]
        trg = LoDTensor([row.reshape(-1, 1) for row in prefix])
        lbl = trg  # unused by the pruned fetch, but the program declares it
        feed = {"src_word": LoDTensor(src_beam), "trg_word": trg, "lbl_word": lbl}
        (logits,) = exe.run(infer_program, feed=feed, fetch_list=[logits_var],
                            scope=scope)
        logits = np.asarray(logits)  # [bk, T>=t, V]
        step_logits = logits[:, t - 1, :].reshape(b, k, -1)
        m = step_logits.max(-1, keepdims=True)  # stable log softmax
        logp = step_logits - m - np.log(np.exp(step_logits - m).sum(-1, keepdims=True))
        V = logp.shape[-1]
        # finished beams only extend with EOS at no cost
        logp_f = np.full_like(logp, -1e9)
        logp_f[:, :, eos] = 0.0
        logp = np.where(finished[:, :, None], logp_f, logp)
        cand = scores[:, :, None] + logp  # [b, k, V]
        flat = cand.reshape(b, k * V)
        top = np.argsort(-flat, axis=1)[:, :k]  # [b, k]
        new_scores = np.take_along_axis(flat, top, axis=1)
        parent = top // V
        token = top % V
        new_seqs = np.empty_like(seqs)
        for i in range(b):
            new_seqs[i] = seqs[i, parent[i]]
            new_seqs[i, :, t] = token[i]
        seqs = new_seqs
        finished = np.take_along_axis(finished, parent, axis=1) | (token == eos)
        scores = new_scores
        if finished.all():
            break

    if length_penalty:
        lengths = (seqs != eos).sum(-1)
        scores = scores / (lengths ** length_penalty)
    best = np.argmax(scores, axis=1)
    return (np.stack([seqs[i, best[i]] for i in range(b)]),
            np.asarray([scores[i, best[i]] for i in range(b)]))


def build_beam_decode(
    src_vocab=1000,
    tgt_vocab=1000,
    d_model=256,
    n_layers=2,
    n_heads=4,
    d_ff=1024,
    batch_size=4,
    src_len=16,
    beam_size=4,
    max_len=12,
    bos=1,
    eos=2,
    length_penalty=0.0,
):
    """Whole-beam-search decode compiled END TO END: encoder once, then a
    layers.While whose body runs the full decoder + the beam_search op over
    static [b, k, L] state — ONE XLA program, zero host round-trips per
    step (the TPU-native answer to the reference's
    while_op + LoDTensorArray + beam_search_op pipeline, layers/nn.py
    beam_search / operators/math/beam_search.cc:24).

    Parameter names match build_transformer_nmt exactly, so weights trained
    there load directly (same scope).  Static bucket: (batch_size, src_len);
    feeds: src_word [b, src_len] int64 (0-padded), src_len_vec [b] int32.
    Fetches: out_ids [b, max_len], out_scores [b].
    """
    import numpy as np

    from ..core.program import Program, program_guard

    b, k, L, Ts = batch_size, beam_size, max_len, src_len
    main, startup = Program(), Program()
    with program_guard(main, startup):
        src = layers.data("src_word", [Ts], dtype="int64")
        src_lens = layers.data("src_len_vec", [], dtype="int32")

        # ---- encoder (params: src.emb, enc{i}.*; _embed/_mha shared with
        # the training builder so names line up) ----------------------------
        # additive key mask from lengths: (b, 1, 1, Ts), 0 inside, -1e9 pad
        mask = layers.sequence_mask(src_lens, Ts, dtype="float32")  # [b,Ts]
        enc_bias = layers.key_padding_bias(mask)                 # [b,1,1,Ts]

        enc = _embed(src, src_vocab, d_model, "src", 0.0, True)
        for i in range(n_layers):
            p = f"enc{i}"
            enc = _add_norm(enc, _mha(enc, enc, enc_bias, d_model, n_heads,
                                      f"{p}.attn", 0.0, True), f"{p}.a", 0.0, True)
            enc = _add_norm(enc, _ffn(enc, d_model, d_ff, f"{p}.ffn", 0.0, True),
                            f"{p}.f", 0.0, True)

        # repeat encoder output + cross bias per beam (row-major [b*k])
        enc4 = layers.reshape(enc, [-1, 1, Ts, d_model])
        enc4 = layers.expand(enc4, [1, k, 1, 1])
        enc_rep = layers.reshape(enc4, [-1, Ts, d_model])        # [bk, Ts, d]
        cb4 = layers.expand(layers.reshape(enc_bias, [-1, 1, 1, Ts]), [1, k, 1, 1])
        cross_bias = layers.reshape(cb4, [-1, 1, 1, Ts])         # [bk,1,1,Ts]

        # ---- beam state ---------------------------------------------------
        seqs0 = np.full((b, k, L), eos, dtype="int64")
        seqs0[:, :, 0] = bos
        scores0 = np.full((b, k), -1e9, dtype="float32")
        scores0[:, 0] = 0.0
        seqs = layers.assign(seqs0)
        scores = layers.assign(scores0)
        finished = layers.assign(np.zeros((b, k), dtype="bool"))
        t = layers.assign(np.asarray([1], dtype="int32"))
        max_t = layers.assign(np.asarray([L], dtype="int32"))
        bk_total = layers.assign(np.asarray([float(b * k)], dtype="float32"))
        causal = np.triu(np.full((L, L), -1e9, np.float32), k=1).reshape(1, 1, L, L)
        self_bias = layers.assign(causal)

        cond = layers.less_than(t, max_t)
        w = layers.While(cond)
        with w.block():
            trg = layers.reshape(seqs, [-1, L])                  # [bk, L]
            dec = _embed(trg, tgt_vocab, d_model, "tgt", 0.0, True)
            for i in range(n_layers):
                p = f"dec{i}"
                dec = _add_norm(dec, _mha(dec, dec, self_bias, d_model,
                                          n_heads, f"{p}.self", 0.0, True),
                                f"{p}.s", 0.0, True)
                dec = _add_norm(dec, _mha(dec, enc_rep, cross_bias, d_model,
                                          n_heads, f"{p}.cross", 0.0, True),
                                f"{p}.c", 0.0, True)
                dec = _add_norm(dec, _ffn(dec, d_model, d_ff, f"{p}.ffn", 0.0, True),
                                f"{p}.f", 0.0, True)
            logits = layers.fc(dec, tgt_vocab, num_flatten_dims=2,
                               param_attr=_attr("proj.w"), bias_attr=_attr("proj.b"))
            layers.beam_search(logits, seqs, scores, finished, t,
                               beam_size=k, end_id=eos)
            layers.increment(t, value=1)
            # continue while t < L and any beam alive
            n_done = layers.reduce_sum(layers.cast(finished, "float32"))
            still_t = layers.less_than(t, max_t)
            alive = layers.less_than(layers.reshape(n_done, [1]), bk_total)
            layers.logical_and(still_t, alive, out=cond)

        out_ids, out_scores = layers.beam_search_decode(
            seqs, scores, end_id=eos, length_penalty=length_penalty)

    feeds = {"src_word": src, "src_len_vec": src_lens}
    return main, startup, feeds, {"out_ids": out_ids, "out_scores": out_scores}
