"""Model zoo mirroring the reference benchmark models
(reference: benchmark/fluid/models/__init__.py:16-19 — machine_translation,
resnet, vgg, mnist, stacked_dynamic_lstm, se_resnext + BERT/Transformer
targets from BASELINE.md)."""
from . import mnist, nmt, resnet, transformer  # noqa: F401
from . import vision  # noqa: F401
from . import deepfm  # noqa: F401
