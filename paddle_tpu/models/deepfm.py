"""DeepFM CTR model (reference shape: tests/unittests/dist_ctr.py +
ctr_dataset reader — sparse categorical slots through an embedding into a
deep MLP, plus a wide/FM part; BASELINE.md names "DeepFM / wide&deep CTR
(sparse LookupTable + PS path)" as a target).

TPU-first: one [B, F] int feed of field ids (static shapes; the reference's
per-slot LoD feeds become fixed fields), `is_sparse=True` tables whose
gradients are SelectedRows slabs (core/selected_rows.py), and optional
`ep`-axis table sharding for the distributed-lookup-table capability
(parallel/embedding.py).
"""
from __future__ import annotations

from .. import layers, optimizer
from ..core.param_attr import ParamAttr
from ..core.program import Program, program_guard


def deepfm_net(feat_ids, num_fields, vocab_size, embed_dim=8, mlp_dims=(64, 32),
               is_sparse=True):
    """feat_ids: [B, F] int64; returns (logit [B,1], prediction [B,1])."""
    # first-order (wide) term: V x 1 table
    w_emb = layers.embedding(feat_ids, size=[vocab_size, 1], is_sparse=is_sparse,
                             param_attr=ParamAttr(name="deepfm_w"))  # [B, F, 1]
    first_order = layers.reduce_sum(w_emb, dim=[1, 2], keep_dim=False)  # [B]

    # second-order FM term over shared V x K factors
    v_emb = layers.embedding(feat_ids, size=[vocab_size, embed_dim], is_sparse=is_sparse,
                             param_attr=ParamAttr(name="deepfm_v"))  # [B, F, K]
    sum_v = layers.reduce_sum(v_emb, dim=[1])           # [B, K]
    sum_sq = layers.square(sum_v)                        # (sum v)^2
    sq_sum = layers.reduce_sum(layers.square(v_emb), dim=[1])  # sum v^2
    fm = layers.reduce_sum(sum_sq - sq_sum, dim=[1]) * 0.5     # [B]

    # deep part: field embeddings through an MLP
    deep = layers.reshape(v_emb, [-1, num_fields * embed_dim])
    for d in mlp_dims:
        deep = layers.fc(deep, size=d, act="relu")
    deep = layers.fc(deep, size=1)                       # [B, 1]

    logit = layers.reshape(first_order + fm, [-1, 1]) + deep
    return logit, layers.sigmoid(logit)


def build(num_fields=8, vocab_size=1000, embed_dim=8, mlp_dims=(64, 32),
          learning_rate=0.05, is_sparse=True, with_optimizer=True,
          opt="adagrad"):
    """Returns (main, startup, feeds, fetches) for CTR training with a
    sigmoid cross-entropy loss (reference dist_ctr.py uses log_loss over a
    softmax pair; sigmoid-CE is the same objective for binary CTR)."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        feat_ids = layers.data("feat_ids", [num_fields], dtype="int64")
        label = layers.data("label", [1], dtype="float32")
        logit, pred = deepfm_net(feat_ids, num_fields, vocab_size, embed_dim,
                                 mlp_dims, is_sparse=is_sparse)
        loss = layers.mean(layers.sigmoid_cross_entropy_with_logits(logit, label))
        if with_optimizer:
            opt_cls = {"adagrad": optimizer.Adagrad, "adam": optimizer.Adam,
                       "sgd": optimizer.SGD}[opt]
            opt_cls(learning_rate=learning_rate).minimize(loss)
    return main, startup, {"feat_ids": feat_ids, "label": label}, \
        {"loss": loss, "prediction": pred}
