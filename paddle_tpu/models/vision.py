"""Remaining benchmark/fluid model builders: VGG, SE-ResNeXt, and the
stacked dynamic LSTM (reference: benchmark/fluid/models/{vgg,se_resnext,
stacked_dynamic_lstm}.py — the fluid_benchmark model list)."""
from __future__ import annotations

from .. import layers, nets, optimizer
from ..core.param_attr import ParamAttr
from ..core.program import Program, program_guard


# --- VGG-16 (benchmark/fluid/models/vgg.py) ---------------------------------

def vgg16(input, class_dim=1000, is_test=False):
    def block(x, nf, n):
        return nets.img_conv_group(
            x, conv_num_filter=[nf] * n, pool_size=2, conv_padding=1,
            conv_filter_size=3, conv_act="relu", conv_with_batchnorm=True,
            pool_stride=2, pool_type="max")

    x = block(input, 64, 2)
    x = block(x, 128, 2)
    x = block(x, 256, 3)
    x = block(x, 512, 3)
    x = block(x, 512, 3)
    flat_dim = 512 * (input.shape[2] // 32) * (input.shape[3] // 32)
    x = layers.reshape(x, [-1, int(flat_dim)])
    x = layers.dropout(x, dropout_prob=0.5, is_test=is_test)
    x = layers.fc(x, 512, act=None)
    x = layers.batch_norm(x, act="relu", is_test=is_test)
    x = layers.dropout(x, dropout_prob=0.5, is_test=is_test)
    x = layers.fc(x, 512, act=None)
    return layers.fc(x, class_dim)


def build_vgg(class_dim=10, image_shape=(3, 32, 32), learning_rate=0.01,
              with_optimizer=True, is_test=False):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("img", list(image_shape), dtype="float32")
        label = layers.data("label", [1], dtype="int64")
        logits = vgg16(img, class_dim=class_dim, is_test=is_test)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        if with_optimizer:
            optimizer.Adam(learning_rate=learning_rate).minimize(loss)
    return main, startup, {"img": img, "label": label}, {"loss": loss, "acc": acc}


# --- SE-ResNeXt-50 (benchmark/fluid/models/se_resnext.py) -------------------

def _squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = layers.pool2d(input, pool_type="avg", global_pooling=True)
    pool = layers.reshape(pool, [-1, num_channels])
    squeeze = layers.fc(pool, num_channels // reduction_ratio, act="relu")
    excitation = layers.fc(squeeze, num_channels, act="sigmoid")
    excitation = layers.reshape(excitation, [-1, num_channels, 1, 1])
    return layers.elementwise_mul(input, excitation, axis=0)


def _conv_bn(input, num_filters, filter_size, stride=1, groups=1, act=None,
             is_test=False):
    conv = layers.conv2d(input, num_filters=num_filters, filter_size=filter_size,
                         stride=stride, padding=(filter_size - 1) // 2,
                         groups=groups, bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _se_bottleneck(input, num_filters, stride, cardinality=32, is_test=False):
    ch_in = input.shape[1]
    conv0 = _conv_bn(input, num_filters, 1, act="relu", is_test=is_test)
    conv1 = _conv_bn(conv0, num_filters, 3, stride=stride, groups=cardinality,
                     act="relu", is_test=is_test)
    conv2 = _conv_bn(conv1, num_filters * 2, 1, is_test=is_test)
    scaled = _squeeze_excitation(conv2, num_filters * 2)
    if ch_in != num_filters * 2 or stride != 1:
        short = _conv_bn(input, num_filters * 2, 1, stride=stride, is_test=is_test)
    else:
        short = input
    return layers.elementwise_add(short, scaled, act="relu")


def se_resnext50(input, class_dim=1000, is_test=False):
    x = _conv_bn(input, 64, 7, stride=2, act="relu", is_test=is_test)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max")
    for filters, blocks, stride in ((128, 3, 1), (256, 4, 2), (512, 6, 2), (1024, 3, 2)):
        for i in range(blocks):
            x = _se_bottleneck(x, filters, stride if i == 0 else 1, is_test=is_test)
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    flat = layers.reshape(pool, [-1, int(pool.shape[1])])
    drop = layers.dropout(flat, dropout_prob=0.2, is_test=is_test)
    return layers.fc(drop, class_dim)


def build_se_resnext(class_dim=1000, image_shape=(3, 224, 224), learning_rate=0.1,
                     with_optimizer=True, is_test=False):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("img", list(image_shape), dtype="float32")
        label = layers.data("label", [1], dtype="int64")
        logits = se_resnext50(img, class_dim=class_dim, is_test=is_test)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        if with_optimizer:
            optimizer.Momentum(learning_rate=learning_rate, momentum=0.9).minimize(loss)
    return main, startup, {"img": img, "label": label}, {"loss": loss}


# --- stacked dynamic LSTM (benchmark/fluid/models/stacked_dynamic_lstm.py) --

def build_stacked_dynamic_lstm(vocab_size=5000, emb_dim=64, hidden_dim=64,
                               stacked_num=3, class_dim=2, learning_rate=0.002,
                               with_optimizer=True):
    """IMDB-style sentiment classifier: embedding -> N stacked dynamic LSTMs
    -> last-step pool -> fc (ragged inputs end to end)."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        words = layers.data("words", [1], dtype="int64", lod_level=1)
        label = layers.data("label", [1], dtype="int64")
        emb = layers.embedding(words, size=[vocab_size, emb_dim])
        h = emb
        for i in range(stacked_num):
            proj = layers.fc(h, 4 * hidden_dim, num_flatten_dims=2,
                             param_attr=ParamAttr(name=f"sl{i}.proj.w"))
            h, _ = layers.dynamic_lstm(
                proj, size=4 * hidden_dim, use_peepholes=False,
                param_attr=ParamAttr(name=f"sl{i}.lstm.w"),
                bias_attr=ParamAttr(name=f"sl{i}.lstm.b"))
        last = layers.sequence_last_step(h)
        logits = layers.fc(last, class_dim)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        if with_optimizer:
            optimizer.Adam(learning_rate=learning_rate).minimize(loss)
    return main, startup, {"words": words, "label": label}, {"loss": loss, "acc": acc}


# --- word2vec (book test: test_word2vec.py N-gram model) --------------------

def build_word2vec(dict_size=1000, embed_size=32, hidden_size=64, n=4,
                   learning_rate=0.01, with_optimizer=True):
    """N-gram language model: (n-1) context words -> next-word softmax
    (reference book/test_word2vec.py network)."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        words = [layers.data(f"w{i}", [1], dtype="int64") for i in range(n - 1)]
        target = layers.data("target", [1], dtype="int64")
        embs = [layers.embedding(w, size=[dict_size, embed_size],
                                 param_attr=ParamAttr(name="w2v_emb"))
                for w in words]
        concat = layers.concat(embs, axis=1)
        hidden = layers.fc(concat, hidden_size, act="sigmoid")
        logits = layers.fc(hidden, dict_size)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, target))
        if with_optimizer:
            optimizer.Adam(learning_rate=learning_rate).minimize(loss)
    feeds = {f"w{i}": w for i, w in enumerate(words)}
    feeds["target"] = target
    return main, startup, feeds, {"loss": loss}
