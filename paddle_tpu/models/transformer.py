"""Transformer encoder / BERT-style pretraining model.

Reference builds transformers from the same primitive layers
(tests/unittests/dist_transformer.py; BERT-base is the BASELINE.md pod
target).  This builder emits fc/matmul/layer_norm/softmax program ops;
attention is plain batched matmul, which XLA maps onto the MXU.

`tp_rules()` returns the sharding-hint ruleset for Megatron-style tensor
parallelism (QKV/FFN1 column-parallel, proj/FFN2 row-parallel) — a new
capability vs the reference (SURVEY.md §2c: TP absent in 2019).
"""
from __future__ import annotations

import numpy as np

from .. import layers, optimizer
from ..core.initializer import NormalInitializer
from ..core.param_attr import ParamAttr
from ..core.program import Program, program_guard


def _attr(name):
    return ParamAttr(name=name, initializer=NormalInitializer(0.0, 0.02))


def multi_head_attention(x, seq_len, d_model, n_heads, prefix, dropout_prob=0.1, is_test=False,
                         use_ring_attention=False, causal=False, kv=None, bias=None,
                         use_fused_attention=False, score_dtype=None):
    """Self- or cross-attention over [b, T, d] (T may be dynamic: head
    split/merge uses fluid's 0-copy-dim reshape).  `kv` switches to
    cross-attention (keys/values from another sequence); `bias` is an
    additive [b, 1, Tq, Tk] pre-softmax mask (layers.attention_bias).
    Serves both the fixed-length BERT builder and the ragged NMT model."""
    d_head = d_model // n_heads
    kv_in = kv if kv is not None else x
    q = layers.fc(x, d_model, num_flatten_dims=2, param_attr=_attr(f"{prefix}.q.w"), bias_attr=_attr(f"{prefix}.q.b"))
    k = layers.fc(kv_in, d_model, num_flatten_dims=2, param_attr=_attr(f"{prefix}.k.w"), bias_attr=_attr(f"{prefix}.k.b"))
    v = layers.fc(kv_in, d_model, num_flatten_dims=2, param_attr=_attr(f"{prefix}.v.w"), bias_attr=_attr(f"{prefix}.v.b"))

    def split_heads(t):
        t = layers.reshape(t, [0, 0, n_heads, d_head])
        return layers.transpose(t, [0, 2, 1, 3])  # (B, H, L, dh)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    if use_fused_attention:
        # Pallas flash kernel: scores never hit HBM.  Attention-prob dropout
        # can't run inside the fused kernel; the equivalent regularization
        # goes on the attention output (same substitution as the ring path).
        ctx = layers.fused_attention(q, k, v, bias=bias, causal=causal,
                                     score_dtype=score_dtype)
        if dropout_prob and not is_test:
            ctx = layers.dropout(ctx, dropout_prob, is_test=is_test,
                                 dropout_implementation="upscale_in_train")
    elif use_ring_attention:
        # sequence-parallel blockwise attention (L shards over the sp axis);
        # attention-prob dropout can't be applied inside the ring, so the
        # equivalent regularization goes on the attention output instead
        ctx = layers.ring_attention(q, k, v, causal=causal)
        if dropout_prob and not is_test:
            ctx = layers.dropout(ctx, dropout_prob, is_test=is_test,
                                 dropout_implementation="upscale_in_train")
    else:
        scores = layers.matmul(q, k, transpose_y=True, alpha=1.0 / np.sqrt(d_head))
        if bias is not None:
            scores = layers.elementwise_add(scores, bias)
        attn = layers.softmax(scores)
        if dropout_prob and not is_test:
            attn = layers.dropout(attn, dropout_prob, is_test=is_test,
                                  dropout_implementation="upscale_in_train")
        ctx = layers.matmul(attn, v)  # (B, H, L, dh)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, d_model])
    return layers.fc(ctx, d_model, num_flatten_dims=2,
                     param_attr=_attr(f"{prefix}.out.w"), bias_attr=_attr(f"{prefix}.out.b"))


def encoder_layer(x, seq_len, d_model, n_heads, d_ff, prefix, dropout_prob=0.1, is_test=False,
                  use_ring_attention=False, causal=False, use_fused_attention=False,
                  score_dtype=None):
    attn_out = multi_head_attention(x, seq_len, d_model, n_heads, f"{prefix}.attn",
                                    dropout_prob, is_test, use_ring_attention, causal,
                                    use_fused_attention=use_fused_attention,
                                    score_dtype=score_dtype)
    x = layers.layer_norm(layers.elementwise_add(x, attn_out), begin_norm_axis=2,
                          param_attr=_attr(f"{prefix}.ln1.w"), bias_attr=_attr(f"{prefix}.ln1.b"))
    ffn1 = layers.fc(x, d_ff, num_flatten_dims=2, act="gelu",
                     param_attr=_attr(f"{prefix}.ffn1.w"), bias_attr=_attr(f"{prefix}.ffn1.b"))
    ffn2 = layers.fc(ffn1, d_model, num_flatten_dims=2,
                     param_attr=_attr(f"{prefix}.ffn2.w"), bias_attr=_attr(f"{prefix}.ffn2.b"))
    if dropout_prob and not is_test:
        ffn2 = layers.dropout(ffn2, dropout_prob, is_test=is_test,
                              dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, ffn2), begin_norm_axis=2,
                             param_attr=_attr(f"{prefix}.ln2.w"), bias_attr=_attr(f"{prefix}.ln2.b"))


def build_bert(
    vocab_size=30522,
    seq_len=128,
    d_model=768,
    n_layers=12,
    n_heads=12,
    d_ff=3072,
    dropout_prob=0.1,
    learning_rate=1e-4,
    with_optimizer=True,
    is_test=False,
    use_ring_attention=False,
    causal=False,
    use_fused_attention=False,
    dtype="float32",
    attention_score_dtype=None,
):
    """BERT-base-style masked-LM pretraining program.

    feeds: ids (B,L) int64, labels (B,L) int64 (-100 = unmasked/ignored).
    dtype="bfloat16" runs the encoder + LM head matmuls on the MXU in bf16
    (master weights stay f32 via per-op match_dtype; LN stats and the loss
    stay f32) — the TPU answer to the reference's fp16 AMP decorator.
    """
    main, startup = Program(), Program()
    with program_guard(main, startup):
        ids = layers.data("ids", [seq_len], dtype="int64")
        labels = layers.data("labels", [seq_len], dtype="int64")
        tok = layers.embedding(ids, size=[vocab_size, d_model], param_attr=_attr("bert.tok_emb"))
        pos_ids = layers.data("pos_ids", [seq_len], dtype="int64")
        pos = layers.embedding(pos_ids, size=[seq_len, d_model], param_attr=_attr("bert.pos_emb"))
        x = layers.elementwise_add(tok, pos)
        x = layers.layer_norm(x, begin_norm_axis=2, param_attr=_attr("bert.emb_ln.w"),
                              bias_attr=_attr("bert.emb_ln.b"))
        if dtype != "float32":
            x = layers.cast(x, dtype)
        for i in range(n_layers):
            x = encoder_layer(x, seq_len, d_model, n_heads, d_ff, f"bert.l{i}",
                              dropout_prob, is_test, use_ring_attention, causal,
                              use_fused_attention=use_fused_attention,
                              score_dtype=attention_score_dtype)
        logits = layers.fc(x, vocab_size, num_flatten_dims=2,
                           param_attr=_attr("bert.lm_head.w"), bias_attr=_attr("bert.lm_head.b"))
        # bf16 logits feed the CE directly: softmax_with_cross_entropy does
        # its reductions in f32 without materializing [N,V] f32 logp, so the
        # old cast here only added ~8 GB/step of HBM traffic at V=30522
        flat_logits = layers.reshape(logits, [-1, vocab_size])
        flat_labels = layers.reshape(labels, [-1, 1])
        loss_per = layers.softmax_with_cross_entropy(flat_logits, flat_labels, ignore_index=-100)
        loss = layers.mean(loss_per)
        if with_optimizer:
            optimizer.Adam(learning_rate=learning_rate).minimize(loss)
    return main, startup, {"ids": ids, "labels": labels, "pos_ids": pos_ids}, {"loss": loss}


def tp_rules():
    """Megatron-style TP sharding hints: QKV & FFN1 column-parallel,
    attn-out & FFN2 row-parallel, embeddings vocab-sharded."""
    return {
        r".*\.attn\.[qkv]\.w": (None, "tp"),
        r".*\.attn\.[qkv]\.b": ("tp",),
        r".*\.attn\.out\.w": ("tp", None),
        r".*\.ffn1\.w": (None, "tp"),
        r".*\.ffn1\.b": ("tp",),
        r".*\.ffn2\.w": ("tp", None),
        r"bert\.tok_emb": ("tp", None),
        r"bert\.lm_head\.w": (None, "tp"),
    }


def make_fake_batch(batch_size, seq_len, vocab_size, rng=None, mask_frac=0.15):
    rng = rng or np.random.RandomState(0)
    ids = rng.randint(0, vocab_size, size=(batch_size, seq_len))
    labels = np.where(rng.rand(batch_size, seq_len) < mask_frac, ids, -100)
    pos = np.tile(np.arange(seq_len), (batch_size, 1))
    return {"ids": ids, "labels": labels, "pos_ids": pos}
