"""ResNet (reference: benchmark/fluid/models/resnet.py — conv_bn_layer /
shortcut / bottleneck structure; ResNet-50 = depth [3,4,6,3]).

The builder emits plain conv2d/batch_norm/pool2d program ops; XLA fuses
BN+ReLU into the convs, which is what made the reference need cuDNN fused
kernels.  Default dtype float32; pass dtype="bfloat16" for the MXU-native
path (loss/metrics stay fp32 via the final cast).

data_format="NHWC" builds the whole model channels-last: every conv/pool/BN
op carries the NHWC attr, feeds are [H,W,C], and the program contains zero
transpose ops — XLA keeps activations in the TPU-native layout end to end
(the round-2 per-op-transpose variant was a measured regression; this is
the whole-model variant docs/perf_r02.md calls for).
"""
from __future__ import annotations

from .. import layers, optimizer
from ..core.program import Program, program_guard


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu", is_test=False,
                  data_format="NCHW"):
    conv = layers.conv2d(input, num_filters=ch_out, filter_size=filter_size, stride=stride,
                         padding=padding, bias_attr=False, data_format=data_format)
    return layers.batch_norm(conv, act=act, is_test=is_test, data_layout=data_format)


def shortcut(input, ch_out, stride, is_test=False, data_format="NCHW"):
    ch_in = input.shape[1] if data_format == "NCHW" else input.shape[3]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None, is_test=is_test,
                             data_format=data_format)
    return input


def bottleneck(input, ch_out, stride, is_test=False, data_format="NCHW"):
    short = shortcut(input, ch_out * 4, stride, is_test=is_test, data_format=data_format)
    conv1 = conv_bn_layer(input, ch_out, 1, 1, 0, is_test=is_test, data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, stride, 1, is_test=is_test, data_format=data_format)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None, is_test=is_test,
                          data_format=data_format)
    return layers.elementwise_add(short, conv3, act="relu")


def basicblock(input, ch_out, stride, is_test=False, data_format="NCHW"):
    short = shortcut(input, ch_out, stride, is_test=is_test, data_format=data_format)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test, data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test,
                          data_format=data_format)
    return layers.elementwise_add(short, conv2, act="relu")


def layer_warp(block_fn, input, ch_out, count, stride, is_test=False, data_format="NCHW"):
    res = block_fn(input, ch_out, stride, is_test=is_test, data_format=data_format)
    for _ in range(1, count):
        res = block_fn(res, ch_out, 1, is_test=is_test, data_format=data_format)
    return res


_DEPTH = {
    18: (basicblock, [2, 2, 2, 2]),
    34: (basicblock, [3, 4, 6, 3]),
    50: (bottleneck, [3, 4, 6, 3]),
    101: (bottleneck, [3, 4, 23, 3]),
    152: (bottleneck, [3, 8, 36, 3]),
}


def _s2d_stem(input, is_test=False):
    """MLPerf-style space-to-depth stem (NCHW): rearrange 224^2 x3 ->
    112^2 x12 with reshape/transpose (channel = c*4 + dy*2 + dx), then a
    4x4 STRIDE-1 conv — mathematically equivalent to the 7x7/s2 stem under
    the weight embedding w4[o, c*4+dy*2+dx, r, s] = w8[o, c, 2r+dy, 2s+dx]
    with w8 = 7x7 kernel zero-padded at offset (1,1) (tests/test_s2d_stem.py
    asserts exact equality).  Why: the 7x7/s2 conv on 3 channels is the
    worst-filled MXU op in the model (docs/perf_r03.md); stride-1 on 12
    channels tiles better.  Asymmetric padding (2 top/left, 1 bottom/right)
    yields exactly the 112^2 output positions of the original stem — the
    symmetric-pad-2 + slice variant was a measured regression
    (docs/perf_r04.md)."""
    b, c, h, w = input.shape
    x6 = layers.reshape(input, [-1, c, h // 2, 2, w // 2, 2])   # b c j dy i dx
    x6 = layers.transpose(x6, [0, 1, 3, 5, 2, 4])               # b c dy dx j i
    s2d = layers.reshape(x6, [-1, c * 4, h // 2, w // 2])
    # asymmetric pad (2,1): exactly the 112 positions of the 7x7/s2 stem,
    # no off-by-one column + slice copy
    conv = layers.conv2d(s2d, num_filters=64, filter_size=4, stride=1,
                         padding=[2, 1, 2, 1], bias_attr=False)
    return layers.batch_norm(conv, act="relu", is_test=is_test)


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False, data_format="NCHW",
                    stem="conv7"):
    block_fn, stages = _DEPTH[depth]
    if stem == "space_to_depth":
        if data_format != "NCHW":
            raise ValueError("space_to_depth stem is NCHW-only")
        conv = _s2d_stem(input, is_test=is_test)
    else:
        conv = conv_bn_layer(input, 64, 7, 2, 3, is_test=is_test, data_format=data_format)
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max",
                         data_format=data_format)
    res = pool
    for i, count in enumerate(stages):
        res = layer_warp(block_fn, res, 64 * (2 ** i), count, 1 if i == 0 else 2,
                         is_test=is_test, data_format=data_format)
    pool2 = layers.pool2d(res, pool_type="avg", global_pooling=True, data_format=data_format)
    flat_ch = pool2.shape[1] if data_format == "NCHW" else pool2.shape[3]
    flat = layers.reshape(pool2, [-1, int(flat_ch)])
    return layers.fc(flat, size=class_dim)


def build(depth=50, class_dim=1000, image_shape=None, learning_rate=0.1,
          momentum=0.9, with_optimizer=True, dtype="float32", is_test=False,
          data_format="NCHW", stem="conv7"):
    """Returns (main, startup, feeds, fetches) for ImageNet-style training.

    dtype="bfloat16" casts the input into bf16 so every conv/matmul hits the
    MXU in its native type; master weights stay fp32 (XLA upcasts per-op
    operands as needed) and the loss is computed in fp32.
    """
    if image_shape is None:
        image_shape = (3, 224, 224) if data_format == "NCHW" else (224, 224, 3)
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("img", list(image_shape), dtype="float32")
        label = layers.data("label", [1], dtype="int64")
        net_in = layers.cast(img, dtype) if dtype != "float32" else img
        logits = resnet_imagenet(net_in, class_dim=class_dim, depth=depth, is_test=is_test,
                                 data_format=data_format, stem=stem)
        logits = layers.cast(logits, "float32") if dtype != "float32" else logits
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        if with_optimizer:
            optimizer.Momentum(learning_rate=learning_rate, momentum=momentum).minimize(loss)
    return main, startup, {"img": img, "label": label}, {"loss": loss, "acc": acc, "logits": logits}
