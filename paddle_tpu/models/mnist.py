"""MNIST CNN (reference: benchmark/fluid/models/mnist.py — conv-pool x2 +
fc stack, softmax CE loss, Adam)."""
from __future__ import annotations

from .. import layers, optimizer
from ..core.program import Program, program_guard


def conv_pool(input, num_filters, filter_size, pool_size, pool_stride, act):
    conv = layers.conv2d(input, num_filters=num_filters, filter_size=filter_size, act=act)
    return layers.pool2d(conv, pool_size=pool_size, pool_stride=pool_stride)


def build(batch_size=None, learning_rate=1e-3, with_optimizer=True):
    """Returns (main, startup, feeds, fetches) for the LeNet-5-ish model."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("img", [1, 28, 28], dtype="float32")
        label = layers.data("label", [1], dtype="int64")
        c1 = conv_pool(img, 20, 5, 2, 2, "relu")
        c2 = conv_pool(c1, 50, 5, 2, 2, "relu")
        flat = layers.reshape(c2, [-1, 50 * 4 * 4])
        hidden = layers.fc(flat, size=500, act="relu")
        logits = layers.fc(hidden, size=10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        if with_optimizer:
            optimizer.Adam(learning_rate=learning_rate).minimize(loss)
    return main, startup, {"img": img, "label": label}, {"loss": loss, "acc": acc, "logits": logits}
